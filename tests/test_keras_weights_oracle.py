"""Keras hdf5 weight-import oracle tests.

Real tf.keras (Keras 3) models are saved to legacy hdf5 and re-imported via
``load_keras``; predictions must match keras' own. This covers the fused
weight layout (kernel/recurrent_kernel/bias). The Keras-1.2.2 per-gate
layout the reference pins (ref: pyspark/bigdl/keras/converter.py:218-241)
is validated by writing the SAME weights in keras-1 form and asserting the
two imports agree.
"""

import json

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = tf.keras

import jax.numpy as jnp  # noqa: E402

from bigdl_tpu.keras.converter import load_keras  # noqa: E402


def _save(tmp_path, model, name):
    h5 = str(tmp_path / f"{name}.h5")
    model.save(h5)
    return model.to_json(), h5


def _forward(model, x):
    model.evaluate()  # inference mode (dropout off, BN running stats)
    return np.asarray(model.forward(jnp.asarray(x)))


# ------------------------------------------------------------- fused layout
def test_lstm_text_model_matches_keras(tmp_path):
    np.random.seed(1)
    km = keras.Sequential([
        keras.layers.Embedding(50, 8),
        keras.layers.LSTM(6),
        keras.layers.Dense(3, activation="softmax"),
    ])
    km.build((None, 12))
    js, h5 = _save(tmp_path, km, "lstm")
    x = np.random.randint(0, 50, (4, 12))
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5, input_shape=(12,))
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def test_lstm_return_sequences_matches_keras(tmp_path):
    np.random.seed(2)
    km = keras.Sequential([
        keras.layers.Embedding(30, 5),
        keras.layers.LSTM(4, return_sequences=True),
    ])
    km.build((None, 7))
    js, h5 = _save(tmp_path, km, "lstm_seq")
    x = np.random.randint(0, 30, (3, 7))
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5, input_shape=(7,))
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def test_gru_model_matches_keras(tmp_path):
    np.random.seed(3)
    km = keras.Sequential([
        keras.layers.Embedding(40, 6),
        keras.layers.GRU(5, reset_after=False),
        keras.layers.Dense(2),
    ])
    km.build((None, 9))
    js, h5 = _save(tmp_path, km, "gru")
    x = np.random.randint(0, 40, (4, 9))
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5, input_shape=(9,))
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def test_gru_reset_after_is_rejected(tmp_path):
    km = keras.Sequential([
        keras.layers.Embedding(10, 4),
        keras.layers.GRU(3, reset_after=True),
    ])
    km.build((None, 5))
    js, h5 = _save(tmp_path, km, "gru_ra")
    with pytest.raises(ValueError, match="reset_after"):
        load_keras(json_str=js, hdf5_path=h5, input_shape=(5,))


def test_simplernn_model_matches_keras(tmp_path):
    np.random.seed(4)
    km = keras.Sequential([
        keras.layers.Embedding(20, 4),
        keras.layers.SimpleRNN(6),
        keras.layers.Dense(2, activation="tanh"),
    ])
    km.build((None, 8))
    js, h5 = _save(tmp_path, km, "rnn")
    x = np.random.randint(0, 20, (3, 8))
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5, input_shape=(8,))
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def test_conv1d_model_matches_keras(tmp_path):
    np.random.seed(5)
    km = keras.Sequential([
        keras.layers.Embedding(25, 6),
        keras.layers.Conv1D(7, 3, activation="relu"),
        keras.layers.GlobalMaxPooling1D(),
        keras.layers.Dense(3),
    ])
    km.build((None, 10))
    js, h5 = _save(tmp_path, km, "conv1d")
    x = np.random.randint(0, 25, (4, 10))
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5, input_shape=(10,))
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def _to_th_json(js: str) -> str:
    """Rewrite a channels_last keras json to the th (channels-first) layout
    our importer pins (the reference is th-only too): drop data_format and
    transpose any input shape from (..., C) to (C, ...)."""
    spec = json.loads(js)
    layers = spec["config"]["layers"] if isinstance(spec["config"], dict) \
        else spec["config"]
    for l in layers:
        c = l["config"]
        c.pop("data_format", None)
        for key in ("batch_shape", "batch_input_shape"):
            if c.get(key) and len(c[key]) == 4:
                b, h, w, ch = c[key]
                c[key] = [b, ch, h, w]
    return json.dumps(spec)


def test_conv2d_separable_model_matches_keras(tmp_path):
    np.random.seed(6)
    km = keras.Sequential([
        keras.layers.Input((8, 8, 2)),
        keras.layers.Conv2D(4, 3, activation="relu"),
        keras.layers.SeparableConv2D(6, 3, depth_multiplier=2),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(3),
    ])
    js, h5 = _save(tmp_path, km, "conv2d")
    x = np.random.randn(2, 8, 8, 2).astype(np.float32)
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=_to_th_json(js), hdf5_path=h5)
    got = _forward(m, x.transpose(0, 3, 1, 2))  # NHWC -> NCHW
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# ----------------------------------------------- keras-1.2.2 per-gate layout
def _write_k1_h5(path, groups):
    """Write {layer_name: [arrays]} in the Keras-1 hdf5 layout."""
    import h5py

    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = [n.encode() for n in groups]
        for ln, arrs in groups.items():
            g = f.create_group(ln)
            names = [f"{ln}_W_{i}".encode() for i in range(len(arrs))]
            g.attrs["weight_names"] = names
            for n, a in zip(names, arrs):
                g.create_dataset(n.decode(), data=a)


K1_LSTM_JSON = json.dumps({"class_name": "Sequential", "config": [
    {"class_name": "LSTM", "config": {
        "output_dim": 4, "return_sequences": False,
        "batch_input_shape": [None, 6, 3]}},
]})


def test_keras1_lstm_pergate_layout_equals_fused(tmp_path):
    rng = np.random.RandomState(7)
    h = 4
    per = {g: (rng.randn(3, h).astype(np.float32),
               rng.randn(h, h).astype(np.float32),
               rng.randn(h).astype(np.float32))
           for g in "icfo"}
    # keras-1 group order i, c, f, o; fused (tf.keras) order i, f, c, o
    k1 = [a for g in "icfo" for a in per[g]]
    fused = [np.concatenate([per[g][0] for g in "ifco"], 1),
             np.concatenate([per[g][1] for g in "ifco"], 1),
             np.concatenate([per[g][2] for g in "ifco"])]
    p1, p2 = str(tmp_path / "k1.h5"), str(tmp_path / "k2.h5")
    _write_k1_h5(p1, {"lstm_1": k1})
    _write_k1_h5(p2, {"lstm_1": fused})
    x = rng.randn(2, 6, 3).astype(np.float32)
    m1 = load_keras(json_str=K1_LSTM_JSON, hdf5_path=p1)
    m2 = load_keras(json_str=K1_LSTM_JSON, hdf5_path=p2)
    np.testing.assert_allclose(_forward(m1, x), _forward(m2, x), rtol=1e-6)


K1_GRU_JSON = json.dumps({"class_name": "Sequential", "config": [
    {"class_name": "GRU", "config": {
        "output_dim": 4, "return_sequences": False,
        "batch_input_shape": [None, 6, 3]}},
]})


def test_keras1_gru_pergate_layout_equals_fused(tmp_path):
    rng = np.random.RandomState(8)
    h = 4
    per = {g: (rng.randn(3, h).astype(np.float32),
               rng.randn(h, h).astype(np.float32),
               rng.randn(h).astype(np.float32))
           for g in "zrh"}
    k1 = [a for g in "zrh" for a in per[g]]  # keras-1 groups z, r, h
    fused = [np.concatenate([per[g][0] for g in "zrh"], 1),
             np.concatenate([per[g][1] for g in "zrh"], 1),
             np.concatenate([per[g][2] for g in "zrh"])]
    p1, p2 = str(tmp_path / "k1.h5"), str(tmp_path / "k2.h5")
    _write_k1_h5(p1, {"gru_1": k1})
    _write_k1_h5(p2, {"gru_1": fused})
    x = rng.randn(2, 6, 3).astype(np.float32)
    m1 = load_keras(json_str=K1_GRU_JSON, hdf5_path=p1)
    m2 = load_keras(json_str=K1_GRU_JSON, hdf5_path=p2)
    np.testing.assert_allclose(_forward(m1, x), _forward(m2, x), rtol=1e-6)


# ------------------------------------------------------- functional API
def test_functional_model_with_merges_matches_keras(tmp_path):
    """Functional import: residual Add + Concatenate wired through the nn
    Graph engine, weights matched BY NAME from the hdf5."""
    np.random.seed(9)
    inp = keras.Input((6,))
    a = keras.layers.Dense(8, activation="relu", name="da")(inp)
    b = keras.layers.Dense(8, name="db")(inp)
    added = keras.layers.Add()([a, b])
    cat = keras.layers.Concatenate()([added, a])
    out = keras.layers.Dense(3, name="head")(cat)
    km = keras.Model(inp, out)
    js, h5 = _save(tmp_path, km, "func")
    x = np.random.randn(4, 6).astype(np.float32)
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5)
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def test_functional_lstm_matches_keras(tmp_path):
    np.random.seed(10)
    inp = keras.Input((7,))
    e = keras.layers.Embedding(30, 5, name="emb")(inp)
    h = keras.layers.LSTM(4, name="rnn")(e)
    out = keras.layers.Dense(2, name="out")(h)
    km = keras.Model(inp, out)
    js, h5 = _save(tmp_path, km, "func_lstm")
    x = np.random.randint(0, 30, (3, 7))
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5)
    got = np.asarray(m.forward(jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_functional_shared_layer_matches_keras(tmp_path):
    """SHARED layers import: two branches through the same weight-owning
    Dense (siamese shape), predictions match keras' own."""
    np.random.seed(12)
    inp_a = keras.Input((4,), name="ia")
    inp_b = keras.Input((4,), name="ib")
    d = keras.layers.Dense(6, activation="relu", name="shared")
    out = keras.layers.Dense(2, name="head")(
        keras.layers.Concatenate()([d(inp_a), d(inp_b)]))
    km = keras.Model([inp_a, inp_b], out)
    js, h5 = _save(tmp_path, km, "shared")
    xa = np.random.randn(3, 4).astype(np.float32)
    xb = np.random.randn(3, 4).astype(np.float32)
    want = km.predict([xa, xb], verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5)
    m.evaluate()
    from bigdl_tpu import T

    got = np.asarray(m.forward(T(jnp.asarray(xa), jnp.asarray(xb))))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_functional_chained_self_share_matches_keras(tmp_path):
    """z = f(f(x)): call node 1's source is the layer's OWN call node 0 —
    the incremental wiring must resolve the chain, and both applications
    share one weight set."""
    np.random.seed(13)
    inp = keras.Input((5,))
    f = keras.layers.Dense(5, activation="tanh", name="f")
    out = keras.layers.Dense(2, name="head")(f(f(inp)))
    km = keras.Model(inp, out)
    js, h5 = _save(tmp_path, km, "selfshare")
    x = np.random.randn(4, 5).astype(np.float32)
    want = km.predict(x, verbose=0)
    m = load_keras(json_str=js, hdf5_path=h5)
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def test_functional_variable_dim_input_uses_override(tmp_path):
    inp = keras.Input((None, 5))  # variable time dim
    h = keras.layers.LSTM(3, name="r")(inp)
    km = keras.Model(inp, h)
    js, h5 = _save(tmp_path, km, "vardim")
    with pytest.raises(ValueError, match="input_shape"):
        load_keras(json_str=js, hdf5_path=h5)
    m = load_keras(json_str=js, hdf5_path=h5, input_shape=(6, 5))
    x = np.random.RandomState(11).randn(2, 6, 5).astype(np.float32)
    want = km.predict(x, verbose=0)
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)


def test_load_keras_from_h5_alone_uses_embedded_config(tmp_path):
    """model.save(...h5) embeds the topology; load_keras(hdf5_path=...)
    alone must reconstruct AND load weights from the one file."""
    np.random.seed(12)
    km = keras.Sequential([
        keras.layers.Embedding(20, 4),
        keras.layers.GRU(3, reset_after=False),
        keras.layers.Dense(2),
    ])
    km.build((None, 6))
    h5 = str(tmp_path / "solo.h5")
    km.save(h5)
    x = np.random.randint(0, 20, (3, 6))
    want = km.predict(x, verbose=0)
    m = load_keras(hdf5_path=h5, input_shape=(6,))
    np.testing.assert_allclose(_forward(m, x), want, rtol=1e-4, atol=1e-5)
