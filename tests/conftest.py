"""Test harness config.

Mirrors the reference's "distributed tested via in-process multi-device"
strategy (SURVEY.md §4): Spark local-mode ≙ a virtual 8-device CPU platform
(``xla_force_host_platform_device_count``). Must run before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fixed_seed():
    from bigdl_tpu.utils import random as bt_random

    bt_random.set_seed(42)
    yield
