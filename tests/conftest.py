"""Test harness config.

Mirrors the reference's "distributed tested via in-process multi-device"
strategy (SURVEY.md §4): Spark local-mode ≙ a virtual 8-device CPU platform
(``xla_force_host_platform_device_count``).

NOTE on env ordering: this image registers the axon TPU PJRT plugin from
sitecustomize at interpreter start; setting JAX_PLATFORMS=cpu in the
environment *before* startup deadlocks that registration. So instead we
switch platform post-import via ``jax.config.update`` — XLA_FLAGS is read at
backend-creation time, which happens on first device use, after this file.
"""

import gc
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fixed_seed():
    from bigdl_tpu.utils import random as bt_random

    bt_random.set_seed(42)
    yield


# Linux defaults vm.max_map_count to 65530, and every jitted executable
# keeps three anonymous mappings (code / rodata / rwdata) alive for the
# life of the process. The full tier-1 suite compiles tens of thousands
# of distinct programs, which marches the map table toward that ceiling;
# once mmap starts failing, LLVM's JIT segfaults mid-compile (observed
# deterministically at ~64k maps). jax.clear_caches() after a gc pass
# unmaps every executable nothing holds anymore (closed engines,
# torn-down fixtures); still-live jitted closures just recompile on
# next call. 45k leaves ~20k maps of headroom for the busiest module.
_MAP_PRESSURE_LIMIT = 45_000


@pytest.fixture(autouse=True, scope="module")
def _shed_jit_map_pressure():
    yield
    try:
        with open("/proc/self/maps") as f:
            n = sum(1 for _ in f)
    except OSError:
        return
    if n > _MAP_PRESSURE_LIMIT:
        gc.collect()
        jax.clear_caches()
