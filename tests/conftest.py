"""Test harness config.

Mirrors the reference's "distributed tested via in-process multi-device"
strategy (SURVEY.md §4): Spark local-mode ≙ a virtual 8-device CPU platform
(``xla_force_host_platform_device_count``).

NOTE on env ordering: this image registers the axon TPU PJRT plugin from
sitecustomize at interpreter start; setting JAX_PLATFORMS=cpu in the
environment *before* startup deadlocks that registration. So instead we
switch platform post-import via ``jax.config.update`` — XLA_FLAGS is read at
backend-creation time, which happens on first device use, after this file.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fixed_seed():
    from bigdl_tpu.utils import random as bt_random

    bt_random.set_seed(42)
    yield
