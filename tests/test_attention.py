"""Attention + sequence parallelism tests on the virtual 8-device mesh.

Oracle strategy: sharded ring/Ulysses attention must equal full
single-device softmax attention (the framework's RefDistriOptimizer-style
semantic-oracle idiom, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bigdl_tpu import models, nn
from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.parallel import Engine, ring_attention, ulysses_attention
from bigdl_tpu.parallel.tp import (
    shard_params, spec_for_params, transformer_tp_rules,
)


@pytest.fixture
def mesh():
    return Engine.create_mesh([("seq", 8)])


def _qkv(b=2, h=4, t=32, d=16, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(b, h, t, d), jnp.float32)
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh, causal):
        q, k, v = _qkv()
        want = dot_product_attention(q, k, v, causal=causal)

        def body(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=causal)

        got = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=P(None, None, "seq", None),
            out_specs=P(None, None, "seq", None), check_vma=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_bf16_inputs(self, mesh):
        q, k, v = _qkv()
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        want = dot_product_attention(q, k, v, causal=True)

        def body(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=True)

        got = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=P(None, None, "seq", None),
            out_specs=P(None, None, "seq", None), check_vma=False))(qb, kb, vb)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=0.06, atol=0.02)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh, causal):
        q, k, v = _qkv(h=8)
        want = dot_product_attention(q, k, v, causal=causal)

        def body(q, k, v):
            return ulysses_attention(q, k, v, axis_name="seq", causal=causal)

        got = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=P(None, None, "seq", None),
            out_specs=P(None, None, "seq", None), check_vma=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)


class TestTransformer:
    def test_lm_forward_and_grads(self):
        m = models.TransformerLM(64, embed_dim=32, num_heads=4, num_layers=2,
                                 max_len=16)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
        logits = m(ids)
        assert logits.shape == (2, 16, 64)

    def test_sequence_parallel_lm_matches_single_device(self, mesh):
        from bigdl_tpu.nn.module import pure_apply

        m_sp = models.TransformerLM(32, embed_dim=16, num_heads=4,
                                    num_layers=1, max_len=64, causal=True,
                                    sequence_parallel="seq")
        params, buffers = m_sp.params_dict(), m_sp.buffers_dict()
        m_ref = models.TransformerLM(32, embed_dim=16, num_heads=4,
                                     num_layers=1, max_len=64, causal=True)
        m_ref.load_params_dict(params)

        ids = jnp.asarray(np.random.RandomState(1).randint(0, 32, (2, 64)))
        want = m_ref(ids)

        apply_fn = pure_apply(m_sp)

        def body(ids):
            out, _ = apply_fn(params, buffers, ids, rng=None, training=False)
            return out

        got = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(None, "seq"),
            out_specs=P(None, "seq", None), check_vma=False))(ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_tp_sharded_forward_matches_replicated(self):
        mesh = Engine.create_mesh([("data", 2), ("model", 4)])
        m = models.TransformerLM(48, embed_dim=32, num_heads=4, num_layers=2,
                                 max_len=8)
        params, buffers = m.params_dict(), m.buffers_dict()
        ids = jnp.asarray(np.random.RandomState(2).randint(0, 48, (4, 8)))
        want = m(ids)

        from bigdl_tpu.nn.module import pure_apply

        sharded = shard_params(params, mesh, transformer_tp_rules("model"))
        apply_fn = pure_apply(m)

        @jax.jit
        def fwd(p, ids):
            out, _ = apply_fn(p, buffers, ids, rng=None, training=False)
            return out

        got = fwd(sharded, ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_spec_rules_cover_matmul_weights(self):
        m = models.TransformerLM(48, embed_dim=32, num_heads=4, num_layers=1,
                                 max_len=8)
        specs = spec_for_params(m.params_dict(), transformer_tp_rules("model"))
        assert specs["block0"]["attn"]["qkv"]["~params"]["weight"] == P("model", None)
        assert specs["block0"]["fc2"]["~params"]["weight"] == P(None, "model")
        assert specs["ln_f"]["~params"]["weight"] == P()


def test_transformer_remat_grads_match():
    # jax.checkpoint over blocks (remat=True) must not change gradients —
    # module key-splitting happens at trace time so the recompute replays
    # the same dropout draws
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.nn.module import pure_apply
    from bigdl_tpu.utils import random as rnd

    def grads(remat, dropout):
        rnd.set_seed(3)
        m = TransformerLM(50, embed_dim=16, num_heads=2, num_layers=2,
                          max_len=16, dropout=dropout, remat=remat)
        fn = pure_apply(m)
        ids = jnp.arange(16)[None] % 50

        def loss(p):
            out, _ = fn(p, {}, ids, rng=jax.random.PRNGKey(0), training=True)
            return jnp.sum(out ** 2)

        return jax.grad(loss)(m.params_dict())

    # deterministic model: remat must not change gradients at all
    g1, g2 = grads(False, 0.0), grads(True, 0.0)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    # with dropout the draw sequences differ by design, but the remat path
    # must trace cleanly (no tracer leak) and produce finite grads
    gd = grads(True, 0.1)
    for a in jax.tree.leaves(gd):
        assert np.isfinite(np.asarray(a)).all()


# ------------------------------------------------------- KV-cache decoding
def test_kv_cache_decode_matches_full_forward():
    """Each incremental decode_step must reproduce the corresponding
    column of the full causal forward (same params, eval mode)."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(0)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=16)
    m.evaluate()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 10)))
    full = np.asarray(m.forward(ids))          # (2, 10, 32)
    caches = m.init_cache(2, 10)
    for i in range(10):
        logits, caches = m.decode_step(ids[:, i], jnp.int32(i), caches)
        np.testing.assert_allclose(np.asarray(logits), full[:, i],
                                   rtol=2e-4, atol=2e-5, err_msg=f"pos {i}")


def test_continuation_prefill_attends_cached_prefix():
    """forward_prefill(pos0 > 0) must attend over the cached [0, pos0)
    prefix: chunked prefill == one-shot prefill (ADVICE r4 medium)."""
    from bigdl_tpu.nn.attention import MultiHeadAttention
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(3)
    m = MultiHeadAttention(16, 4, num_kv_heads=2, causal=True, rotary=True)
    m.evaluate()
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 16), jnp.float32)
    full, _ = m.forward_prefill(x, m.init_cache(2, 8))
    cache = m.init_cache(2, 8)
    o1, cache = m.forward_prefill(x[:, :5], cache, 0)
    o2, _ = m.forward_prefill(x[:, 5:], cache, 5)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(full[:, :5]),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(full[:, 5:]),
                               rtol=2e-4, atol=2e-5)
    with pytest.raises(TypeError):  # traced pos0 would silently be wrong
        m.forward_prefill(x[:, 5:], cache, jnp.int32(5))


def test_chunked_prefill_generate_matches_one_shot():
    """generate(prefill_chunk=k) must produce the SAME tokens as the
    one-shot prefill: the traced-offset chunk path (one compile per
    chunk length) and the remainder-first split are exact."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(4)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=24, use_rope=True)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(4).randint(0, 32, (2, 11)))
    want = m.generate(prompt, 5)
    got = m.generate(prompt, 5, prefill_chunk=4)   # 3-token remainder + 2x4
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    got_even = m.generate(prompt[:, :8], 5, prefill_chunk=4)  # no remainder
    want_even = m.generate(prompt[:, :8], 5)
    np.testing.assert_array_equal(np.asarray(got_even), np.asarray(want_even))


def test_generate_greedy_extends_prompt():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(1)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_layers=1,
                      max_len=16)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 32, (2, 4)))
    out = m.generate(prompt, max_new_tokens=5)
    assert out.shape == (2, 9)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
    # greedy continuation must match teacher-forced argmax of the full model
    # token at output position 4+i is the argmax of the logits at input
    # position 3+i of the teacher-forced forward over out[:, :8]
    full = m.forward(out[:, :-1])
    np.testing.assert_array_equal(
        np.asarray(out[:, 4:]),
        np.asarray(jnp.argmax(full[:, 3:], axis=-1)))


def test_generate_sampling_deterministic_with_key():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(2)
    m = TransformerLM(32, embed_dim=16, num_heads=2, num_layers=1,
                      max_len=12)
    m.evaluate()
    prompt = jnp.asarray([[1, 2, 3]])
    a = m.generate(prompt, 4, temperature=0.8, rng=jax.random.PRNGKey(7))
    b = m.generate(prompt, 4, temperature=0.8, rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 7)


def test_generate_scan_matches_host_loop():
    """The default one-dispatch lax.scan decode must produce the SAME
    tokens as the one-dispatch-per-token host loop (its parity oracle) —
    greedy, and sampled under the same key (both paths split the key
    once per generated token)."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(11)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=16, use_rope=True)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(4).randint(0, 32, (2, 5)))
    np.testing.assert_array_equal(
        np.asarray(m.generate(prompt, 6)),
        np.asarray(m.generate(prompt, 6, host_loop=True)))
    key = jax.random.PRNGKey(3)
    np.testing.assert_array_equal(
        np.asarray(m.generate(prompt, 6, temperature=0.7, rng=key)),
        np.asarray(m.generate(prompt, 6, temperature=0.7, rng=key,
                              host_loop=True)))
    # bucketed compile length: same tokens, one program per bucket
    np.testing.assert_array_equal(
        np.asarray(m.generate(prompt, 6, bucket_tokens=4)),
        np.asarray(m.generate(prompt, 6)))
    np.testing.assert_array_equal(
        np.asarray(m.generate(prompt, 6, temperature=0.7, rng=key,
                              bucket_tokens=4)),
        np.asarray(m.generate(prompt, 6, temperature=0.7, rng=key)))


def test_generate_eos_and_sampling_filters():
    """eos early-stop pads with eos identically on the scan and host
    paths; top_k=1 sampling degenerates to greedy; top-k/top-p filtered
    sampling stays scan==host bit-identical under one key."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(13)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=24, use_rope=True)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(7).randint(0, 32, (2, 5)))
    greedy = m.generate(prompt, 8)
    # pick the token every row emits first as "eos": terminates at once,
    # so positions 1.. must all be eos on both paths
    eos = int(np.asarray(greedy[0, 5]))
    if not (np.asarray(greedy[:, 5]) == eos).all():
        eos = None  # rows diverge: still exercise parity below
    for kw in ([dict(eos_id=eos)] if eos is not None else []) + [
            dict(temperature=0.9, top_k=4), dict(temperature=0.9, top_p=0.8),
            dict(temperature=0.9, top_k=6, top_p=0.9, eos_id=0)]:
        if kw.get("temperature"):
            kw["rng"] = jax.random.PRNGKey(21)
        a = np.asarray(m.generate(prompt, 8, **kw))
        b_ = np.asarray(m.generate(prompt, 8, host_loop=True, **kw))
        np.testing.assert_array_equal(a, b_), kw
        if kw.get("eos_id") is not None:  # after first eos: all eos
            for row in a[:, 5:]:
                hits = np.where(row == kw["eos_id"])[0]
                if len(hits):
                    assert (row[hits[0]:] == kw["eos_id"]).all(), row
    # top_k=1 == greedy regardless of temperature/key
    np.testing.assert_array_equal(
        np.asarray(m.generate(prompt, 8, temperature=1.3, top_k=1,
                              rng=jax.random.PRNGKey(3))),
        np.asarray(greedy))
    # invalid filter configs fail loudly at the API boundary
    with pytest.raises(ValueError, match="temperature"):
        m.generate(prompt, 4, top_p=0.9)  # greedy would ignore the filter
    with pytest.raises(ValueError, match="top_k"):
        m.generate(prompt, 4, temperature=0.8, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        m.generate(prompt, 4, temperature=0.8, top_p=0.0)


def test_generate_data_parallel_on_mesh():
    """Data-parallel serving: generate() with the prompt batch-sharded
    over an 8-device mesh (params replicated) must produce EXACTLY the
    single-device tokens — the scan decode is pure SPMD, so XLA shards
    the KV caches/logits along batch from the input sharding alone."""
    from jax.sharding import NamedSharding

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(14)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=16, use_rope=True)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(8).randint(0, 32, (8, 5)))
    want = np.asarray(m.generate(prompt, 6))

    mesh = Engine.create_mesh([("data", 8)])
    sharded_prompt = jax.device_put(
        prompt, NamedSharding(mesh, P("data", None)))
    got = m.generate(sharded_prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), want)
    # the decode really ran SPMD: the result is still batch-sharded
    # across all 8 devices (XLA propagated the sharding end to end)
    assert len(got.sharding.device_set) == 8
    assert got.sharding.spec == P("data", None)


def test_generate_sequence_sharded_kv_cache_on_mesh():
    """Long-context serving: the KV caches laid out SHARDED along the
    sequence axis over the 8-device mesh (a context bigger than one
    chip's HBM) must decode the exact single-device tokens — GSPMD
    partitions the attention contractions and softmax reductions from
    the cache sharding alone."""
    from jax.sharding import NamedSharding

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(18)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=16, use_rope=True)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(13).randint(0, 32, (2, 5)))
    want = np.asarray(m.generate(prompt, 6))

    mesh = Engine.create_mesh([("seq", 8)])
    sharding = NamedSharding(mesh, P(None, None, "seq", None))
    got = m.generate(prompt, 6, max_len=16,  # 16 positions / 8 shards
                     kv_cache_sharding=sharding)
    np.testing.assert_array_equal(np.asarray(got), want)
    # the HBM property, not just the tokens: the caches must come out of
    # the jitted prefill still sharded along T across all 8 devices (a
    # GSPMD regression that gathers them would keep tokens identical)
    *_, logits, caches = m._decode_setup(prompt, 6, 16,
                                         kv_cache_sharding=sharding)
    k0 = caches[0][0]
    assert len(k0.sharding.device_set) == 8
    assert k0.sharding.spec == P(None, None, "seq")


def test_generate_tensor_parallel_on_mesh():
    """Megatron-style TP serving: load the LM's params back SHARDED over
    the 8-way model axis (column/row split via transformer_tp_rules) and
    generate() must still produce the single-device tokens — GSPMD
    places the per-layer collectives; no decode-specific TP code."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(15)
    m = TransformerLM(32, embed_dim=32, num_heads=8, num_layers=2,
                      max_len=16, use_rope=True)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(9).randint(0, 32, (2, 5)))
    want = np.asarray(m.generate(prompt, 6))

    mesh = Engine.create_mesh([("model", 8)])
    m.load_params_dict(shard_params(m.params_dict(), mesh,
                                    transformer_tp_rules()))
    got = m.generate(prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), want)


class TestRaggedGenerate:
    """generate_ragged: mixed prompt lengths in one right-padded batch
    must continue every row exactly as generate() would on that row
    alone — the per-row position vector drives the same scan."""

    def _models(self):
        from bigdl_tpu.models.transformer import TransformerLM
        from bigdl_tpu.utils import random as rnd

        out = []
        for seed, rope in ((19, True), (20, False)):
            rnd.set_seed(seed)
            m = TransformerLM(32, embed_dim=16, num_heads=4,
                              num_kv_heads=2 if rope else None,
                              num_layers=2, max_len=32, use_rope=rope)
            m.evaluate()
            out.append(m)
        return out

    def test_rows_match_per_row_generate(self):
        r = np.random.RandomState(14)
        lengths = np.asarray([3, 5, 7, 4])
        tmax = 7
        padded = np.zeros((4, tmax), np.int64)
        rows = []
        for i, L in enumerate(lengths):
            p = r.randint(0, 32, (L,))
            rows.append(p)
            padded[i, :L] = p
        for m in self._models():  # RoPE and learned-positions variants
            got = np.asarray(m.generate_ragged(padded, lengths, 6))
            assert got.shape == (4, 6)
            for i, p in enumerate(rows):
                want = np.asarray(m.generate(jnp.asarray(p)[None], 6))[0]
                np.testing.assert_array_equal(got[i], want[len(p):])

    def test_eos_bucket_and_validation(self):
        m = self._models()[0]
        r = np.random.RandomState(15)
        lengths = np.asarray([2, 6])
        padded = np.zeros((2, 6), np.int64)
        for i, L in enumerate(lengths):
            padded[i, :L] = r.randint(0, 32, (L,))
        # bucketed scan: same tokens as exact length
        np.testing.assert_array_equal(
            np.asarray(m.generate_ragged(padded, lengths, 5,
                                         bucket_tokens=4)),
            np.asarray(m.generate_ragged(padded, lengths, 5)))
        # eos: per-row tails freeze after the first eos
        out = np.asarray(m.generate_ragged(padded, lengths, 8, eos_id=0))
        for row in out:
            hits = np.where(row == 0)[0]
            if len(hits):
                assert (row[hits[0]:] == 0).all(), row
        # sampled mode: deterministic under one key
        k = jax.random.PRNGKey(5)
        np.testing.assert_array_equal(
            np.asarray(m.generate_ragged(padded, lengths, 5,
                                         temperature=0.8, rng=k)),
            np.asarray(m.generate_ragged(padded, lengths, 5,
                                         temperature=0.8, rng=k)))
        with pytest.raises(ValueError, match="lengths"):
            m.generate_ragged(padded, np.asarray([2, 9]), 4)
        with pytest.raises(ValueError, match="context"):
            m.generate_ragged(padded, lengths, 40)


class TestSpeculativeDecoding:
    """speculative_generate must equal target greedy generate() EXACTLY
    regardless of the draft — the draft only changes the round count."""

    def _target(self, seed=16, max_len=48):
        from bigdl_tpu.models.transformer import TransformerLM
        from bigdl_tpu.utils import random as rnd

        rnd.set_seed(seed)
        m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                          num_layers=2, max_len=max_len, use_rope=True)
        m.evaluate()
        return m

    def test_self_draft_always_accepts(self):
        m = self._target()
        prompt = jnp.asarray(np.random.RandomState(10).randint(0, 32, (2, 5)))
        want = np.asarray(m.generate(prompt, 12))
        # count verify rounds: with draft == target every proposal is
        # accepted, so each round yields gamma+1 tokens -> ceil(11/5)
        # rounds instead of 12 sequential target steps
        real = m._verify_fn(2, 5)
        calls = []

        def counting(*a):
            calls.append(1)
            return real(*a)

        m._verify_fn = lambda b, c: counting
        try:
            got = np.asarray(m.speculative_generate(prompt, 12, draft=m,
                                                    gamma=4))
        finally:
            del m._verify_fn  # restore the class method
        np.testing.assert_array_equal(got, want)
        assert len(calls) == 3, calls  # 1 prefill token + 3x(4+1) >= 12

    def test_unrelated_draft_still_exact(self):
        m = self._target()
        d = self._target(seed=99)  # different weights: rarely accepts
        prompt = jnp.asarray(np.random.RandomState(11).randint(0, 32, (3, 4)))
        np.testing.assert_array_equal(
            np.asarray(m.speculative_generate(prompt, 10, draft=d, gamma=3)),
            np.asarray(m.generate(prompt, 10)))

    def test_quantized_draft_exact(self):
        from bigdl_tpu.nn.quantized import Quantizer

        m = self._target(seed=17)
        d = Quantizer.quantize(m)
        d.evaluate()
        prompt = jnp.asarray(np.random.RandomState(12).randint(0, 32, (2, 6)))
        np.testing.assert_array_equal(
            np.asarray(m.speculative_generate(prompt, 9, draft=d, gamma=4)),
            np.asarray(m.generate(prompt, 9)))

    def test_spec_accept_identity_matches_target_distribution(self):
        """The speculative-sampling core (Leviathan Thm 1): proposal
        where accepted, residual where rejected, must be distributed
        EXACTLY as the target p — pinned empirically over 40k trials
        against arbitrary (p, q) pairs."""
        from bigdl_tpu.models.transformer import _spec_accept

        v, trials, temp = 8, 40000, 0.7
        r = np.random.RandomState(30)
        p_row = r.randn(v) * 1.5
        q_row = r.randn(v) * 1.5
        # broadcast one (p, q) pair over `trials` rows; gamma = 1
        p_logits = jnp.broadcast_to(jnp.asarray(p_row, jnp.float32),
                                    (trials, 2, v))  # bonus row unused
        q_logits = jnp.broadcast_to(jnp.asarray(q_row, jnp.float32),
                                    (trials, 1, v))
        qdist = np.asarray(jax.nn.softmax(jnp.asarray(q_row) / temp))
        props = jnp.asarray(
            r.choice(v, size=(trials, 1), p=qdist), jnp.int32)
        accept, resid, _ = _spec_accept(p_logits, q_logits, props,
                                        jnp.float32(temp),
                                        jax.random.PRNGKey(31))
        got = np.asarray(jnp.where(accept[:, 0], props[:, 0],
                                   resid[:, 0]))
        freq = np.bincount(got, minlength=v) / trials
        want = np.asarray(jax.nn.softmax(jnp.asarray(p_row) / temp))
        # 40k trials: per-bin standard error < ~0.25% — 2% tolerance
        np.testing.assert_allclose(freq, want, atol=0.02)

    def test_sampled_self_draft_accepts_everything(self):
        m = self._target(seed=23)
        prompt = jnp.asarray(np.random.RandomState(16).randint(0, 32,
                                                               (2, 4)))
        ids, st = m.speculative_generate(
            prompt, 11, draft=m, gamma=4, temperature=0.8,
            rng=jax.random.PRNGKey(7), return_stats=True)
        assert ids.shape == (2, 15)
        # p == q -> U < 1: every proposal accepted up to ulp-level
        # drift between the chunked-verify and single-step compute
        # paths (exact on CPU; tolerant for low-precision backends)
        assert st["accept_rate"] >= 0.9, st
        assert st["rounds"] <= 3, st  # near 1 prefill token + 2x(4+1)

    def test_sampled_unrelated_draft_serves_deterministically(self):
        m = self._target(seed=24)
        d = self._target(seed=25)
        prompt = jnp.asarray(np.random.RandomState(17).randint(0, 32,
                                                               (2, 5)))
        k = jax.random.PRNGKey(9)
        a = m.speculative_generate(prompt, 9, draft=d, gamma=3,
                                   temperature=0.9, rng=k)
        b_ = m.speculative_generate(prompt, 9, draft=d, gamma=3,
                                    temperature=0.9, rng=k)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
        assert a.shape == (2, 14)
        assert (np.asarray(a) >= 0).all() and (np.asarray(a) < 32).all()

    def test_tight_context_shrinks_gamma_and_stays_exact(self):
        m = self._target(max_len=12)
        prompt = jnp.asarray([[1, 2, 3, 4]])
        # t0 + n == max_len: one slack position left -> gamma shrinks to
        # 1 (the cap is ctx - t0 - n + 1) and the output stays exact
        np.testing.assert_array_equal(
            np.asarray(m.speculative_generate(prompt, 8, draft=m, gamma=4)),
            np.asarray(m.generate(prompt, 8)))
        # explicit gamma=0 falls back to the plain greedy path
        np.testing.assert_array_equal(
            np.asarray(m.speculative_generate(prompt, 8, draft=m, gamma=0)),
            np.asarray(m.generate(prompt, 8)))


def test_generate_rejects_prompt_plus_tokens_over_max_len():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(3)
    m = TransformerLM(16, embed_dim=8, num_heads=2, num_layers=1, max_len=16)
    with pytest.raises(ValueError, match="max_len"):
        m.generate(jnp.asarray([[1, 2, 3, 4]]), 10, max_len=8)


# --------------------------------------------------------------- RoPE
def test_rotary_embedding_matches_manual_rotation():
    from bigdl_tpu.nn.attention import rotary_embedding

    x = jnp.asarray(np.random.RandomState(0).randn(1, 1, 3, 4), jnp.float32)
    pos = jnp.arange(3)
    got = np.asarray(rotary_embedding(x, pos))
    base = 10000.0
    want = np.zeros_like(got)
    for t in range(3):
        for j in range(2):  # feature pairs (0,1) and (2,3)
            theta = t / base ** (2 * j / 4)
            c, s = np.cos(theta), np.sin(theta)
            x1, x2 = float(x[0, 0, t, 2 * j]), float(x[0, 0, t, 2 * j + 1])
            want[0, 0, t, 2 * j] = x1 * c - x2 * s
            want[0, 0, t, 2 * j + 1] = x1 * s + x2 * c
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_rope_attention_is_shift_invariant():
    """RoPE scores depend only on relative positions: attention outputs of
    a window are unchanged when the whole window shifts (causal within)."""
    from bigdl_tpu.nn.attention import dot_product_attention, rotary_embedding

    q, k, v = (jnp.asarray(np.random.RandomState(i).randn(1, 2, 6, 8),
                           jnp.float32) for i in range(3))

    def attend(shift):
        pos = shift + jnp.arange(6)
        return dot_product_attention(rotary_embedding(q, pos),
                                     rotary_embedding(k, pos), v,
                                     causal=True)

    np.testing.assert_allclose(np.asarray(attend(0)), np.asarray(attend(5)),
                               rtol=1e-4, atol=1e-5)


def test_rope_lm_decode_matches_full_forward():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(4)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=16, use_rope=True)
    m.evaluate()
    assert "pos_embed" not in m.params_dict()  # no learned table
    ids = jnp.asarray(np.random.RandomState(4).randint(0, 32, (2, 9)))
    full = np.asarray(m.forward(ids))
    caches = m.init_cache(2, 9)
    for i in range(9):
        logits, caches = m.decode_step(ids[:, i], jnp.int32(i), caches)
        np.testing.assert_allclose(np.asarray(logits), full[:, i],
                                   rtol=2e-4, atol=2e-5, err_msg=f"pos {i}")
    out = m.generate(ids[:, :3], 4)
    assert out.shape == (2, 7)


def test_rope_lm_sequence_parallel_matches_single_device(mesh):
    from bigdl_tpu.nn.module import pure_apply
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(5)
    m_sp = models.TransformerLM(32, embed_dim=16, num_heads=4, num_layers=1,
                                max_len=64, causal=True, use_rope=True,
                                sequence_parallel="seq")
    params, buffers = m_sp.params_dict(), m_sp.buffers_dict()
    m_ref = models.TransformerLM(32, embed_dim=16, num_heads=4, num_layers=1,
                                 max_len=64, causal=True, use_rope=True)
    m_ref.load_params_dict(params)
    ids = jnp.asarray(np.random.RandomState(5).randint(0, 32, (2, 64)))
    want = m_ref(ids)
    apply_fn = pure_apply(m_sp)

    def body(ids):
        out, _ = apply_fn(params, buffers, ids, rng=None, training=False)
        return out

    got = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=P(None, "seq"),
        out_specs=P(None, "seq", None), check_vma=False))(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_rotary_rejects_odd_head_dim():
    from bigdl_tpu.nn.attention import MultiHeadAttention

    with pytest.raises(ValueError, match="even head_dim"):
        MultiHeadAttention(6, num_heads=2, rotary=True)


def test_beam_search_k1_equals_greedy():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(6)
    m = TransformerLM(32, embed_dim=16, num_heads=2, num_layers=1,
                      max_len=16)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(6).randint(0, 32, (2, 4)))
    greedy = m.generate(prompt, 6)
    beam = m.beam_search(prompt, 6, num_beams=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(beam))


def test_beam_search_improves_or_matches_sequence_logprob():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    def seq_logprob(m, seq, t0):
        logp = jax.nn.log_softmax(m.forward(seq[:, :-1]).astype(jnp.float32))
        tok = seq[:, 1:]
        ll = jnp.take_along_axis(logp, tok[..., None], -1)[..., 0]
        return np.asarray(ll[:, t0 - 1:].sum(axis=1))

    rnd.set_seed(7)
    m = TransformerLM(32, embed_dim=16, num_heads=2, num_layers=1,
                      max_len=16)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(7).randint(0, 32, (3, 4)))
    greedy = m.generate(prompt, 8)
    beam = m.beam_search(prompt, 8, num_beams=4)
    assert beam.shape == greedy.shape == (3, 12)
    lg, lb = seq_logprob(m, greedy, 4), seq_logprob(m, beam, 4)
    assert (lb >= lg - 1e-4).all(), (lb, lg)


def test_beam_scan_matches_host_loop():
    """The default one-dispatch scanned beam search (parent-pointer
    backtracking) must match the per-step host loop exactly — with and
    without eos freezing, and under length penalties."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(12)
    m = TransformerLM(32, embed_dim=16, num_heads=2, num_layers=2,
                      max_len=24)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(5).randint(0, 32, (3, 4)))
    for kw in [dict(num_beams=4), dict(num_beams=3, eos_id=0),
               dict(num_beams=4, length_penalty=0.7)]:
        np.testing.assert_array_equal(
            np.asarray(m.beam_search(prompt, 7, **kw)),
            np.asarray(m.beam_search(prompt, 7, host_loop=True, **kw)))
    np.testing.assert_array_equal(  # n=1: zero-length scan edge
        np.asarray(m.beam_search(prompt, 1, num_beams=4)),
        np.asarray(m.beam_search(prompt, 1, num_beams=4, host_loop=True)))


def test_beam_search_freezes_finished_beams_on_eos():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(8)
    m = TransformerLM(16, embed_dim=8, num_heads=2, num_layers=1,
                      max_len=20)
    m.evaluate()
    prompt = jnp.asarray([[1, 2]])
    out = np.asarray(m.beam_search(prompt, 10, num_beams=3, eos_id=0))
    gen = out[0, 2:]
    eos_pos = np.where(gen == 0)[0]
    if len(eos_pos):  # everything after the first eos must stay eos
        assert (gen[eos_pos[0]:] == 0).all(), gen


# ------------------------------------------------- ring + flash composition
class TestRingFlash:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention_fwd_and_grads(self, mesh, causal):
        """Flash-kernel ring steps (lse-space merge, custom vjp carrying
        the lse cotangent) must equal full softmax attention — forward AND
        gradients — with T/n = 128-wide local blocks."""
        b, h, t, d = 1, 2, 1024, 16  # 8 devices -> 128-long local blocks
        r = np.random.RandomState(0)
        q, k, v = (jnp.asarray(r.randn(b, h, t, d) * 0.3, jnp.float32)
                   for _ in range(3))

        def full_sum(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

        def ring_sum(q, k, v):
            def body(q, k, v):
                return ring_attention(q, k, v, axis_name="seq",
                                      causal=causal, use_flash=True)

            out = jax.shard_map(
                body, mesh=mesh,
                in_specs=P(None, None, "seq", None),
                out_specs=P(None, None, "seq", None), check_vma=False)(q, k, v)
            return jnp.sum(out ** 2)

        f = jax.jit(jax.value_and_grad(full_sum, argnums=(0, 1, 2)))
        g = jax.jit(jax.value_and_grad(ring_sum, argnums=(0, 1, 2)))
        want_v, want_g = f(q, k, v)
        got_v, got_g = g(q, k, v)
        np.testing.assert_allclose(float(got_v), float(want_v), rtol=2e-4)
        for a, bb in zip(got_g, want_g):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=5e-4, atol=5e-5)

    def test_mha_seq_parallel_flash_matches_dense_ring(self, mesh):
        from bigdl_tpu.nn.module import pure_apply
        from bigdl_tpu.utils import random as rnd

        rnd.set_seed(9)
        m = models.TransformerLM(32, embed_dim=16, num_heads=4, num_layers=1,
                                 max_len=1024, causal=True, use_rope=True,
                                 sequence_parallel="seq", use_flash=True)
        params, buffers = m.params_dict(), m.buffers_dict()
        m_ref = models.TransformerLM(32, embed_dim=16, num_heads=4,
                                     num_layers=1, max_len=1024, causal=True,
                                     use_rope=True)
        m_ref.load_params_dict(params)
        ids = jnp.asarray(np.random.RandomState(9).randint(0, 32, (1, 1024)))
        want = m_ref(ids)
        apply_fn = pure_apply(m)

        def body(ids):
            out, _ = apply_fn(params, buffers, ids, rng=None, training=False)
            return out

        got = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(None, "seq"),
            out_specs=P(None, "seq", None), check_vma=False))(ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-4)


    def test_ring_flash_gqa_rotates_unexpanded_kv(self, mesh):
        """GQA through the flash ring (kv heads rotate un-expanded) must
        equal dense ring attention over explicitly repeated kv heads."""
        b, h, h_kv, t, d = 1, 4, 2, 1024, 16
        r = np.random.RandomState(1)
        q = jnp.asarray(r.randn(b, h, t, d) * 0.3, jnp.float32)
        k = jnp.asarray(r.randn(b, h_kv, t, d) * 0.3, jnp.float32)
        v = jnp.asarray(r.randn(b, h_kv, t, d) * 0.3, jnp.float32)
        want = dot_product_attention(q, jnp.repeat(k, 2, 1),
                                     jnp.repeat(v, 2, 1), causal=True)

        def body(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=True,
                                  use_flash=True)

        got = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=P(None, None, "seq", None),
            out_specs=P(None, None, "seq", None), check_vma=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_flash_falls_back_when_blocks_dont_tile(self, mesh):
        """Non-tiling local block lengths silently use the dense ring."""
        b, h, t, d = 1, 2, 1200, 8  # local t = 150, not a 128 multiple
        r = np.random.RandomState(2)
        q, k, v = (jnp.asarray(r.randn(b, h, t, d) * 0.3, jnp.float32)
                   for _ in range(3))
        want = dot_product_attention(q, k, v, causal=True)

        def body(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=True,
                                  use_flash=True)

        got = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=P(None, None, "seq", None),
            out_specs=P(None, None, "seq", None), check_vma=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


def test_int8_beam_search_and_mesh_ragged_compose():
    """Cross-products of the serving features: the int8-quantized model
    serves through the scanned beam search (eos freezing intact), and
    ragged decode runs SPMD with batch-sharded rows on the 8-device
    mesh, matching the unsharded tokens exactly."""
    from jax.sharding import NamedSharding

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.nn.quantized import Quantizer
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(33)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=24, use_rope=True)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(20).randint(0, 32, (2, 5)))

    q = Quantizer.quantize(m)
    q.evaluate()
    out = np.asarray(q.beam_search(prompt, 8, num_beams=3, eos_id=0))
    assert out.shape == (2, 13)
    for row in out[:, 5:]:
        hits = np.where(row == 0)[0]
        if len(hits):
            assert (row[hits[0]:] == 0).all()

    mesh = Engine.create_mesh([("data", 8)])
    lengths = np.asarray([3, 5, 7, 4, 6, 2, 5, 3])
    padded = np.zeros((8, 7), np.int64)
    r = np.random.RandomState(21)
    for i, L in enumerate(lengths):
        padded[i, :L] = r.randint(0, 32, (L,))
    want = np.asarray(m.generate_ragged(padded, lengths, 6))
    sp = jax.device_put(jnp.asarray(padded, jnp.int32),
                        NamedSharding(mesh, P("data", None)))
    sl = jax.device_put(jnp.asarray(lengths, jnp.int32),
                        NamedSharding(mesh, P("data")))
    np.testing.assert_array_equal(
        np.asarray(m.generate_ragged(sp, sl, 6)), want)


def test_generate_streaming_callback():
    """host_loop streaming: on_token fires once per generated step with
    that step's (B,) tokens, in order, matching the returned ids; eos
    early-exit still pads the RETURN but streams only real steps; the
    scan path rejects on_token loudly."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(34)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_layers=1,
                      max_len=16)
    m.evaluate()
    prompt = jnp.asarray(np.random.RandomState(22).randint(0, 32, (2, 4)))
    streamed = []
    out = m.generate(prompt, 6, host_loop=True,
                     on_token=lambda t: streamed.append(np.asarray(t)))
    assert len(streamed) == 6
    np.testing.assert_array_equal(np.stack(streamed, axis=1),
                                  np.asarray(out[:, 4:]))
    # eos early-exit: the RETURN pads to n, but only real (pre-exit)
    # steps stream — force instant termination by using the first
    # greedy tokens as "eos" for every row
    eos = int(np.asarray(out[0, 4]))
    if (np.asarray(out[:, 4]) == eos).all():
        streamed.clear()
        padded = m.generate(prompt, 6, host_loop=True, eos_id=eos,
                            on_token=lambda t: streamed.append(
                                np.asarray(t)))
        assert padded.shape == (2, 10)
        assert (np.asarray(padded[:, 5:]) == eos).all()
        assert len(streamed) == 1  # one real step, no synthetic pads
    with pytest.raises(ValueError, match="host_loop"):
        m.generate(prompt, 6, on_token=lambda t: None)
