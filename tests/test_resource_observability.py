"""Device-resource observability: memory accounting + pool attribution
(``observability/memory.py``), on-demand profiler capture
(``profiler.py``), recompile/SLO watchdogs (``watchdog.py``), their
engine wiring (pools registered, queue-wait histogram, alerts in
``stats()``/degraded ``/healthz``), the ``/debug/memory`` +
``/debug/profile`` endpoints, and the metrics lint.

The acceptance arc under test: an injected recompile storm and a
synthetic SLO breach each produce a flight-recorder alert event, a
Prometheus alert gauge, and a ``degraded`` healthz body (still HTTP
200 — 503 stays reserved for a crashed loop); ``/debug/memory``
attributes HBM to the KV slot pool, prefill staging, prefix pool, and
params by name; pool gauges move when KV is donated into the prefix
pool.
"""

import gc
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.observability import memory as obs_memory
from bigdl_tpu.observability import profiler
from bigdl_tpu.observability.events import FlightRecorder
from bigdl_tpu.observability.watchdog import (
    RecompileWatchdog, SloObjective, SloWatchdog,
)


@pytest.fixture()
def reg():
    r = obs.MetricRegistry()
    prev = obs.set_default_registry(r)
    try:
        yield r
    finally:
        obs.set_default_registry(prev)


@pytest.fixture()
def rec():
    r = FlightRecorder()
    prev = obs.set_default_recorder(r)
    try:
        yield r
    finally:
        obs.set_default_recorder(prev)


@pytest.fixture(scope="module")
def lm():
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(29)
    m = TransformerLM(32, embed_dim=16, num_heads=4, num_kv_heads=2,
                      num_layers=2, max_len=48, use_rope=True)
    m.evaluate()
    return m


# --------------------------------------------------------- pool registry
class TestPoolRegistry:
    def test_register_and_tree_bytes(self):
        import jax.numpy as jnp

        tree = {"a": jnp.ones((4, 8), jnp.float32),
                "b": [jnp.ones((2,), jnp.int32), None]}
        assert obs_memory.tree_bytes(tree) == 4 * 8 * 4 + 2 * 4
        assert obs_memory.tree_bytes(None) == 0

        name = obs_memory.register_pool("t/static", lambda: 42)
        try:
            assert "t/static" in obs_memory.registered_pools()
            assert obs_memory.pool_sizes()["t/static"] == 42
        finally:
            obs_memory.unregister_pool(name)
        assert "t/static" not in obs_memory.registered_pools()
        # a raising (or non-int) pool is skipped THIS sample but stays
        # registered — transient errors must not delete attribution
        obs_memory.register_pool("t/broken", lambda: 1 // 0)
        obs_memory.register_pool("t/notint", lambda: "nope")
        sizes = obs_memory.pool_sizes()
        assert "t/broken" not in sizes and "t/notint" not in sizes
        assert "t/broken" in obs_memory.registered_pools()
        obs_memory.unregister_pool("t/broken")
        obs_memory.unregister_pool("t/notint")
        # fn-guarded unregister: the wrong fn is a no-op
        fn = lambda: 5  # noqa: E731
        obs_memory.register_pool("t/guarded", fn)
        obs_memory.unregister_pool("t/guarded", lambda: 6)
        assert "t/guarded" in obs_memory.registered_pools()
        obs_memory.unregister_pool("t/guarded", fn)
        assert "t/guarded" not in obs_memory.registered_pools()
        with pytest.raises(ValueError):
            obs_memory.register_pool("", lambda: 0)
        with pytest.raises(TypeError):
            obs_memory.register_pool("t/x", 7)

    def test_weak_owner_pools_pruned_after_collection(self):
        class Owner:
            bytes = 99

        o = Owner()
        obs_memory.register_owned_pools(o, {"t/weak": lambda s: s.bytes})
        assert obs_memory.pool_sizes()["t/weak"] == 99
        del o
        gc.collect()
        # the registration held only a weakref: the pool self-prunes
        assert "t/weak" not in obs_memory.pool_sizes()
        assert "t/weak" not in obs_memory.registered_pools()


# --------------------------------------------------------- memory monitor
def test_memory_monitor_sample_gauges_and_watermark(reg, rec):
    mon = obs.DeviceMemoryMonitor(registry=reg, history=4)
    obs_memory.register_pool("t/mon", lambda: 1000)
    try:
        s = mon.sample()
    finally:
        obs_memory.unregister_pool("t/mon")
    assert s["devices"], "at least one local device"
    d0 = s["devices"][0]
    assert d0["source"] in ("memory_stats", "live_arrays")
    assert d0["bytes_in_use"] >= 0 and s["bytes_in_use"] >= 0
    assert s["pools"]["t/mon"] == 1000
    # gauges landed in THIS registry under the canonical names
    assert reg.get("bigdl_device_hbm_bytes_in_use") \
        .labels("0").get() == d0["bytes_in_use"]
    assert reg.get("bigdl_device_pool_bytes") \
        .labels("t/mon").get() == 1000
    # a pool that disappears is zeroed on the next sample, and the
    # ring + high watermark accumulate
    s2 = mon.sample()
    assert "t/mon" not in s2["pools"]
    assert reg.get("bigdl_device_pool_bytes").labels("t/mon").get() == 0
    dbg = mon.debug_memory()
    assert dbg["peak_bytes"] >= max(s["bytes_in_use"], 1) - 1
    assert dbg["peak"] is not None
    assert 1 <= len(dbg["history"]) <= 4
    assert {"ts", "bytes_in_use", "pools"} <= set(dbg["history"][0])
    # the watermark left a recorder event
    assert any(e.kind == "memory/high_watermark" for e in rec.tail()) \
        or s["bytes_in_use"] == 0


# ------------------------------------------------------ recompile watchdog
def test_recompile_watchdog_storm_fires_and_clears(reg, rec):
    compiles = [0]
    wd = RecompileWatchdog(lambda: compiles[0], service="t",
                           warmup_growths=2, window=16, storm_growths=3,
                           clear_after=4, registry=reg, recorder=rec)
    # warmup growths are free: no alert however fast they come
    for _ in range(3):
        compiles[0] += 1
        assert wd.sample() is False
    # post-warmup growth keeps happening -> storm
    fired_at = None
    for i in range(4):
        compiles[0] += 1
        if wd.sample():
            fired_at = i
            break
    assert fired_at is not None and wd.active
    alert = wd.alert()
    assert alert["alert"] == "recompile_storm"
    assert alert["severity"] == "critical"
    assert reg.get("bigdl_watchdog_alert_active") \
        .labels("recompile_storm", "t").get() == 1
    assert any(e.kind == "watchdog/recompile_storm" for e in rec.tail())
    # stable compiles for clear_after samples -> alert clears
    for _ in range(6):
        wd.sample()
    assert not wd.active and wd.alert() is None
    assert reg.get("bigdl_watchdog_alert_active") \
        .labels("recompile_storm", "t").get() == 0
    assert any(e.kind == "watchdog/recompile_cleared"
               for e in rec.tail())
    # a broken probe is survivable
    bad = RecompileWatchdog(lambda: 1 // 0, registry=reg, recorder=rec)
    assert bad.sample() is False


def test_recompile_watchdog_clear_after_exceeds_window(reg, rec):
    """clear_after > window must hold the alert for the full quiet
    interval — window-pruned storm marks are detection state, not the
    clear countdown."""
    compiles = [0]
    wd = RecompileWatchdog(lambda: compiles[0], service="t2",
                           warmup_growths=0, window=4, storm_growths=2,
                           clear_after=10, registry=reg, recorder=rec)
    wd.sample()
    for _ in range(3):
        compiles[0] += 1
        wd.sample()
    assert wd.active
    # 9 quiet samples: past the window, still inside clear_after
    for _ in range(9):
        wd.sample()
    assert wd.active
    wd.sample()  # 10th quiet sample: clears
    assert not wd.active


# ------------------------------------------------------------ slo watchdog
def test_slo_watchdog_burn_rate_synthetic_timelines(reg, rec):
    hist = reg.histogram("t_latency_seconds", "t",
                         buckets=(0.01, 0.1, 1.0))
    wd = SloWatchdog(service="t", registry=reg, recorder=rec)
    wd.watch(SloObjective("ttft_p90", threshold_s=0.1, target=0.9,
                          window_s=60.0, burn_threshold=2.0,
                          min_count=10), hist._only())
    t = 1000.0
    wd.sample(now=t)
    # healthy traffic: 5% violations < budget*burn_threshold (20%)
    for i in range(40):
        hist.observe(0.5 if i % 20 == 0 else 0.02)
    assert wd.sample(now=t + 10) is False
    # SLO-violating timelines: half the observations blow the threshold
    for i in range(40):
        hist.observe(0.5 if i % 2 == 0 else 0.02)
    assert wd.sample(now=t + 20) is True
    (alert,) = wd.alerts()
    assert alert["alert"] == "slo:ttft_p90"
    assert alert["burn_rate"] >= 2.0
    assert reg.get("bigdl_watchdog_alert_active") \
        .labels("slo:ttft_p90", "t").get() == 1
    assert reg.get("bigdl_watchdog_slo_burn_rate") \
        .labels("ttft_p90", "t").get() == pytest.approx(
            alert["burn_rate"], rel=0.01)
    assert any(e.kind == "watchdog/slo_burn" for e in rec.tail())
    # the violating window ages out under good traffic -> clears
    for _ in range(200):
        hist.observe(0.02)
    assert wd.sample(now=t + 100) is False
    assert wd.alerts() == []
    assert any(e.kind == "watchdog/slo_cleared" for e in rec.tail())
    assert reg.get("bigdl_watchdog_alert_active") \
        .labels("slo:ttft_p90", "t").get() == 0


def test_slo_threshold_between_bucket_edges_rounds_pessimistic(reg, rec):
    """A threshold that is not a bucket edge must round DOWN to the
    previous edge (over-alerting), never up — a watchdog that counts
    2.2s observations as 'good' against a 2.0s objective would sit
    silent through a full breach."""
    hist = reg.histogram("t_mid_seconds", "t", buckets=(1.0, 2.5, 5.0))
    wd = SloWatchdog(service="t", registry=reg, recorder=rec)
    wd.watch(SloObjective("mid", threshold_s=2.0, target=0.9,
                          window_s=60.0, burn_threshold=2.0,
                          min_count=10), hist._only())
    wd.sample(now=500.0)
    for _ in range(20):
        hist.observe(2.2)  # violates the 2.0s objective
    assert wd.sample(now=510.0) is True
    assert wd.alerts()[0]["alert"] == "slo:mid"


def test_slo_objective_validation():
    with pytest.raises(ValueError):
        SloObjective("x", threshold_s=0.1, target=1.0)
    with pytest.raises(ValueError):
        SloObjective("x", threshold_s=0.0)
    with pytest.raises(ValueError):
        SloObjective("x", threshold_s=0.1, window_s=0)


# ------------------------------------------------- engine integration
@pytest.fixture(scope="module")
def engine_run(lm):
    """ONE shared engine + request mix for the integration assertions:
    a hair-trigger TTFT objective (every real request violates 1µs)
    makes the synthetic SLO breach, pools register at construction,
    donations populate the prefix pool."""
    mreg = obs.MetricRegistry()
    prev_reg = obs.set_default_registry(mreg)
    mrec = FlightRecorder()
    prev_rec = obs.set_default_recorder(mrec)
    from bigdl_tpu.serving import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(
        lm, max_slots=2, prefill_chunk=4, service_name="resobs",
        slo_objectives=[dict(name="ttft_p99", metric="ttft",
                             threshold_s=1e-6, target=0.99,
                             window_s=600.0, min_count=2)])
    try:
        with eng:
            r = np.random.RandomState(11)
            handles = [eng.submit(r.randint(0, 32, (t0,)), n)
                       for t0, n in [(5, 4), (9, 3), (6, 4)]]
            for h in handles:
                h.result(timeout=120)
            yield eng, mreg, mrec, handles
    finally:
        obs.set_default_registry(prev_reg)
        obs.set_default_recorder(prev_rec)


def test_engine_pool_attribution_moves_on_donation(engine_run):
    eng, mreg, mrec, handles = engine_run
    sizes = obs_memory.pool_sizes()
    kv = sizes["serving/resobs/kv_slots"]
    assert kv == obs_memory.tree_bytes(eng._caches) > 0
    assert sizes["serving/resobs/prefill_staging"] \
        == obs_memory.tree_bytes(eng._staging) > 0
    assert sizes["serving/resobs/params"] > 0
    assert sizes["serving/resobs/prefix_pool"] == 2 * kv  # 2x slot rows
    # finished slots DONATED their KV: occupied prefix bytes moved off 0
    in_use = sizes["serving/resobs/prefix_kv_in_use"]
    assert in_use == eng._prefix.bytes_in_use > 0
    assert in_use <= sizes["serving/resobs/prefix_pool"]
    # and the monitor publishes the attribution as gauges
    mon = obs.DeviceMemoryMonitor(registry=mreg)
    mon.sample()
    assert mreg.get("bigdl_device_pool_bytes") \
        .labels("serving/resobs/prefix_kv_in_use").get() == in_use


def test_engine_queue_wait_histogram(engine_run):
    eng, mreg, _, handles = engine_run
    _, total, count = mreg.get("bigdl_serving_queue_wait_seconds") \
        .labels("resobs").get()
    assert count == len(handles)
    assert total >= 0.0


def test_engine_slo_breach_degrades_healthz(engine_run):
    eng, mreg, mrec, _ = engine_run
    alerts = eng.stats()["alerts"]
    slo = [a for a in alerts if a["alert"] == "slo:ttft_p99"]
    assert slo, alerts
    assert slo[0]["burn_rate"] >= 2.0
    hz = eng.healthz()
    assert hz["status"] == "degraded" and hz["alerts"]
    assert mreg.get("bigdl_watchdog_alert_active") \
        .labels("slo:ttft_p99", "resobs").get() == 1
    assert any(e.kind == "watchdog/slo_burn" for e in mrec.tail())
    # degraded is 200-with-detail on the endpoint; 503 stays reserved
    # for a crashed loop
    with obs.start_http_server(host="127.0.0.1",
                               healthz=eng.healthz) as srv:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz")
        assert resp.status == 200
        body = json.loads(resp.read())
        assert body["status"] == "degraded"
        assert body["alerts"][0]["alert"] == "slo:ttft_p99"
    assert eng.debug_requests()["alerts"]


def test_debug_memory_endpoint_roundtrip(engine_run):
    eng, mreg, _, _ = engine_run
    mon = obs.DeviceMemoryMonitor(registry=mreg)
    with obs.start_http_server(host="127.0.0.1",
                               debug_memory=mon.debug_memory) as srv:
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/memory").read())
    assert doc["now"]["devices"]
    assert doc["now"]["pools"]["serving/resobs/kv_slots"] \
        == obs_memory.tree_bytes(eng._caches)
    assert doc["peak_bytes"] >= 0 and doc["history"]
    # the default-monitor route answers too (no explicit monitor wired)
    with obs.start_http_server(host="127.0.0.1") as srv:
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/memory").read())
        assert "now" in doc and doc["now"]["devices"]


def test_engine_injected_recompile_storm(engine_run):
    """Last in the shared-engine arc: swap in a hair-trigger watchdog
    over an injected ever-growing compile counter — post-warmup growth
    across loop iterations must raise the storm alert, its gauge, its
    recorder event, and degrade healthz."""
    eng, mreg, mrec, _ = engine_run
    fake = {"n": 0}

    def probe():
        fake["n"] += 1  # "every iteration compiled something new"
        return fake["n"]

    eng._recompile_wd = RecompileWatchdog(
        probe, service="resobs", warmup_growths=1, window=16,
        storm_growths=3, clear_after=1000, registry=mreg, recorder=mrec)
    h = eng.submit(np.arange(1, 6, dtype=np.int32), 8)
    h.result(timeout=120)
    alerts = eng.stats()["alerts"]
    storm = [a for a in alerts if a["alert"] == "recompile_storm"]
    assert storm, alerts
    assert eng.healthz()["status"] == "degraded"
    assert mreg.get("bigdl_watchdog_alert_active") \
        .labels("recompile_storm", "resobs").get() == 1
    assert any(e.kind == "watchdog/recompile_storm"
               for e in mrec.tail())


def test_fresh_engine_stats_latency_never_raises(lm, reg, rec):
    """The percentile façade on a just-constructed engine (no requests,
    loop never started) reports count-0/None summaries instead of
    raising — and a fresh GenerationService does the same."""
    from bigdl_tpu.optim import GenerationService
    from bigdl_tpu.serving import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(lm, max_slots=1, prefill_chunk=4)
    s = eng.stats()
    for phase in ("queue_wait", "prefill", "ttft", "decode", "total"):
        assert s["latency"][phase]["count"] == 0
        assert s["latency"][phase]["p99"] is None
    assert s["alerts"] == []
    assert eng.debug_requests()["latency"]["ttft"]["p50"] is None
    svc = GenerationService(lm, max_batch=2)
    lat = svc.stats()["latency"]
    assert all(v["count"] == 0 and v["p50"] is None
               for v in lat.values())


# ---------------------------------------------------------- profiler
def test_profiler_capture_and_endpoint(reg, rec, tmp_path):
    try:
        path = profiler.capture(0.05, out_dir=str(tmp_path / "prof"))
    except profiler.ProfilerUnavailable as e:
        pytest.skip(f"profiler capture unsupported here: {e}")
    import os
    assert os.path.isdir(path)
    assert sum(len(fs) for _, _, fs in os.walk(path)) > 0
    kinds = [e.kind for e in rec.tail()]
    assert "profiler/capture_start" in kinds
    assert "profiler/capture_done" in kinds
    assert not profiler.capturing()

    with obs.start_http_server(host="127.0.0.1") as srv:
        base = f"http://127.0.0.1:{srv.port}"
        try:
            doc = json.loads(urllib.request.urlopen(
                f"{base}/debug/profile?seconds=0.05").read())
            assert os.path.isdir(doc["artifact"])
        except urllib.error.HTTPError as e:
            assert e.code in (501, 409), e.code
        # POST works; hostile seconds is a 400, not a 500
        req = urllib.request.Request(
            f"{base}/debug/profile?seconds=0.05", data=b"",
            method="POST")
        try:
            doc = json.loads(urllib.request.urlopen(req).read())
            assert os.path.isdir(doc["artifact"])
        except urllib.error.HTTPError as e:
            assert e.code in (501, 409), e.code
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/debug/profile?seconds=nope")
        assert exc.value.code == 400

    with pytest.raises(ValueError):
        profiler.capture(0)


def test_profiler_busy_is_exclusive(tmp_path):
    try:
        profiler.start_capture(str(tmp_path / "p1"))
    except profiler.ProfilerUnavailable as e:
        pytest.skip(f"profiler capture unsupported here: {e}")
    try:
        with pytest.raises(profiler.ProfilerBusy):
            profiler.start_capture(str(tmp_path / "p2"))
    finally:
        assert profiler.stop_capture() is not None
    # idempotent soft stop for timer/finally races
    assert profiler.stop_capture(strict=False) is None
    with pytest.raises(profiler.ProfilerBusy):
        profiler.stop_capture(strict=True)


# -------------------------------------------------------- metrics lint
def _load_lint():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "metrics_lint", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "metrics_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metrics_lint_tree_is_clean(capsys):
    """Tier-1 enforcement of the one-schema rule: no bigdl_* metric is
    registered outside observability/instruments.py anywhere in the
    tree (bench.py included — its gauges moved into instruments)."""
    lint = _load_lint()
    assert lint.main([]) == 0
    assert "ok" in capsys.readouterr().out


def test_metrics_lint_catches_violation(tmp_path, capsys):
    bad = tmp_path / "rogue.py"
    bad.write_text(
        'reg.gauge("bigdl_rogue_bytes", "minted out of place")\n')
    lint = _load_lint()
    assert lint.main(["--root", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "rogue.py" in out and "bigdl_rogue_bytes" in out
    # tests/ and docs/ are out of scope by design
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "t.py").write_text(
        'reg.gauge("bigdl_test_only", "x")\n')
    bad.unlink()
    assert lint.main(["--root", str(tmp_path)]) == 0
