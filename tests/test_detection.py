"""Detection heads (≙ nn/PriorBox.scala, nn/Nms.scala, nn/Proposal.scala,
nn/RoiPooling.scala, nn/DetectionOutputSSD.scala) + vision pipeline."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.detection import (
    Anchor, DetectionOutputSSD, PriorBox, Proposal, RoiPooling, bbox_iou,
    decode_boxes, nms,
)
from bigdl_tpu.utils.table import Table


def test_bbox_iou():
    a = jnp.asarray([[0, 0, 10, 10.0]])
    b = jnp.asarray([[0, 0, 10, 10.0], [5, 5, 15, 15], [20, 20, 30, 30]])
    iou = np.asarray(bbox_iou(a, b))[0]
    np.testing.assert_allclose(iou, [1.0, 25 / 175, 0.0], rtol=1e-5)


def test_nms_suppresses_overlaps():
    boxes = jnp.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30.0]])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    keep, count = nms(scores, boxes, thresh=0.5, topk=3)
    assert int(count) == 2
    assert set(np.asarray(keep)[:2].tolist()) == {0, 2}


def test_nms_jit_compatible():
    boxes = jnp.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30.0]])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    keep, count = jax.jit(lambda s, b: nms(s, b, 0.5, 3))(scores, boxes)
    assert int(count) == 2


def test_prior_box_shapes_and_values():
    pb = PriorBox(min_sizes=[30.0], max_sizes=[60.0],
                  aspect_ratios=[2.0], is_flip=True, is_clip=False,
                  variances=[0.1, 0.1, 0.2, 0.2], img_h=300, img_w=300,
                  step_h=100.0, step_w=100.0)
    # priors per cell: min + max + 2 flipped ratios = 4
    assert pb.num_priors == 4
    fmap = jnp.zeros((1, 8, 3, 3))
    out = np.asarray(pb(fmap))
    assert out.shape == (1, 2, 3 * 3 * 4 * 4)
    boxes = out[0, 0].reshape(-1, 4)
    # first cell center = (0.5*100, 0.5*100) = (50, 50); first box 30x30
    np.testing.assert_allclose(
        boxes[0], [(50 - 15) / 300, (50 - 15) / 300,
                   (50 + 15) / 300, (50 + 15) / 300], rtol=1e-5)
    var = out[0, 1].reshape(-1, 4)
    np.testing.assert_allclose(var[0], [0.1, 0.1, 0.2, 0.2])


def test_decode_boxes_identity_and_shift():
    priors = jnp.asarray([[0.2, 0.2, 0.4, 0.4]])
    vars_ = jnp.asarray([[0.1, 0.1, 0.2, 0.2]])
    out = np.asarray(decode_boxes(priors, vars_, jnp.zeros((1, 4))))
    np.testing.assert_allclose(out, [[0.2, 0.2, 0.4, 0.4]], atol=1e-6)
    # positive dx shifts center right by v*d*w = 0.1*1*0.2 = 0.02
    out = np.asarray(decode_boxes(priors, vars_,
                                  jnp.asarray([[1.0, 0, 0, 0]])))
    np.testing.assert_allclose(out, [[0.22, 0.2, 0.42, 0.4]], atol=1e-6)


def test_anchor_generation():
    a = Anchor(ratios=[1.0], scales=[8.0])
    assert a.num == 1
    base = a.base_anchors[0]
    # 16*8 = 128-wide box centered on the 16x16 base cell
    assert base[2] - base[0] + 1 == 128
    grid = a.generate_anchors(2, 2, feat_stride=16.0)
    assert grid.shape == (4, 4)
    np.testing.assert_allclose(grid[1] - grid[0], [16, 0, 16, 0])


def test_roi_pooling_matches_manual():
    feats = jnp.arange(16.0).reshape(1, 1, 4, 4)
    rois = jnp.asarray([[0, 0, 0, 3, 3.0]])  # whole 4x4 map
    rp = RoiPooling(2, 2, spatial_scale=1.0)
    out = np.asarray(rp(Table(feats, rois)))
    assert out.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_roi_pooling_respects_batch_index_and_scale():
    feats = jnp.stack([jnp.zeros((1, 4, 4)),
                       jnp.arange(16.0).reshape(1, 4, 4)])
    rois = jnp.asarray([[1, 0, 0, 6, 6.0]])  # scale 0.5 -> cover 0..3
    rp = RoiPooling(1, 1, spatial_scale=0.5)
    out = np.asarray(rp(Table(feats, rois)))
    np.testing.assert_allclose(out[0, 0], [[15.0]])


def test_proposal_emits_rois():
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(1)
    prop = Proposal(pre_nms_topn=50, post_nms_topn=5, ratios=[1.0],
                    scales=[4.0])
    prop.evaluate()
    a = prop.anchor.num
    h = w = 4
    rng = np.random.RandomState(0)
    scores = jnp.asarray(rng.rand(1, 2 * a, h, w).astype(np.float32))
    deltas = jnp.asarray(0.1 * rng.randn(1, 4 * a, h, w).astype(np.float32))
    im_info = jnp.asarray([64.0, 64.0, 1.0, 1.0])
    rois = np.asarray(prop(Table(scores, deltas, im_info)))
    assert rois.shape[1] == 5 and 1 <= rois.shape[0] <= 5
    assert np.all(rois[:, 0] == 0)
    assert np.all(rois[:, 1] >= 0) and np.all(rois[:, 3] <= 63)


def test_detection_output_ssd():
    # 2 priors, 3 classes (bg=0); prior 0 strongly class 1, prior 1 class 2
    priors = np.asarray([[0.1, 0.1, 0.3, 0.3], [0.5, 0.5, 0.9, 0.9]],
                        np.float32)
    vars_ = np.full((2, 4), 0.1, np.float32)
    pr = np.stack([priors.reshape(-1), vars_.reshape(-1)])[None]
    loc = np.zeros((1, 8), np.float32)  # zero deltas: boxes = priors
    conf = np.asarray([[0.05, 0.9, 0.05, 0.1, 0.1, 0.8]], np.float32)
    head = DetectionOutputSSD(n_classes=3, conf_thresh=0.3, nms_thresh=0.45)
    out = np.asarray(head(Table(jnp.asarray(loc), jnp.asarray(conf),
                                jnp.asarray(pr))))
    assert out.shape[:2] == (1, 1) and out.shape[3] == 7
    rows = out[0, 0]
    assert rows.shape[0] == 2
    by_label = {int(r[1]): r for r in rows}
    np.testing.assert_allclose(by_label[1][2], 0.9, rtol=1e-5)
    np.testing.assert_allclose(by_label[1][3:], priors[0], atol=1e-5)
    np.testing.assert_allclose(by_label[2][3:], priors[1], atol=1e-5)


def test_image_frame_pipeline_with_roi_transforms():
    from bigdl_tpu.transform.vision import (
        ChannelNormalize, HFlip, ImageFeature, ImageFrame, ImageFeatureToBatch,
        Resize, RoiHFlip, RoiNormalize, RoiResize,
    )

    rng = np.random.RandomState(0)
    imgs = rng.rand(3, 8, 8, 3).astype(np.float32)
    frame = ImageFrame.array(imgs, labels=np.asarray([1, 2, 3]))
    assert len(frame) == 3 and frame.is_local()
    # attach a ground-truth box to every feature
    for f in frame:
        f[ImageFeature.boxes] = np.asarray([[2.0, 2.0, 6.0, 6.0]])

    out = frame.transform(Resize(16, 16)).transform(RoiResize())
    f0 = list(out)[0]
    assert f0.image().shape == (16, 16, 3)
    np.testing.assert_allclose(f0[ImageFeature.boxes], [[4, 4, 12, 12]])

    flipped = out.transform(HFlip()).transform(RoiHFlip(normalized=False))
    b = list(flipped)[0][ImageFeature.boxes]
    np.testing.assert_allclose(b, [[4, 4, 12, 12]])  # symmetric box

    norm = flipped.transform(RoiNormalize())
    b = list(norm)[0][ImageFeature.boxes]
    np.testing.assert_allclose(b, [[0.25, 0.25, 0.75, 0.75]])

    batches = list(ImageFeatureToBatch(3)(iter(
        norm.transform(ChannelNormalize([0.5, 0.5, 0.5])).features)))
    assert len(batches) == 1
    assert batches[0].get_input().shape == (3, 3, 16, 16)


def test_expand_updates_boxes():
    from bigdl_tpu.transform.vision import Expand, ImageFeature

    f = ImageFeature(np.ones((4, 4, 3), np.float32))
    f[ImageFeature.boxes] = np.asarray([[1.0, 1.0, 3.0, 3.0]])
    e = Expand(means=(0, 0, 0), max_expand_ratio=2.0, seed=3)
    out = e.transform(f)
    b = out[ImageFeature.boxes][0]
    h, w = out.image().shape[:2]
    assert h >= 4 and w >= 4
    assert b[0] >= 1.0 - 1e-6 and b[2] <= w


def test_prior_box_derives_img_size_from_table_and_caches():
    pb = PriorBox(min_sizes=[30.0], step_h=100.0, step_w=100.0)
    fmap = jnp.zeros((1, 8, 3, 3))
    data = jnp.zeros((1, 3, 300, 300))
    out1 = pb(Table(fmap, data))
    out2 = pb(Table(fmap, data))
    assert out1 is out2  # cached for static feature/image size
    import pytest as _pytest
    with _pytest.raises(ValueError, match="img_h"):
        PriorBox(min_sizes=[30.0])(fmap)


def test_detection_output_ssd_rejects_unshared_location():
    import pytest as _pytest
    with _pytest.raises(NotImplementedError):
        DetectionOutputSSD(n_classes=3, share_location=False)


def test_proposal_drops_small_boxes():
    prop = Proposal(pre_nms_topn=50, post_nms_topn=10, ratios=[1.0],
                    scales=[1.0], min_size=64)
    prop.evaluate()
    a = prop.anchor.num
    h = w = 2
    rng = np.random.RandomState(0)
    scores = jnp.asarray(rng.rand(1, 2 * a, h, w).astype(np.float32))
    deltas = jnp.zeros((1, 4 * a, h, w), jnp.float32)
    # anchors are 16x16-ish at scale 1 -> all below min_size 64
    rois = np.asarray(prop(Table(scores, deltas,
                                 jnp.asarray([64.0, 64.0, 1.0]))))
    assert rois.shape[0] == 0


def test_vision_augmentation_suite():
    from bigdl_tpu.transform import vision as V

    rng = np.random.RandomState(0)
    img = rng.rand(16, 16, 3).astype(np.float32) * 255

    f = V.ImageFeature(img.copy())
    V.Contrast(1.2, 1.2).transform(f)
    np.testing.assert_allclose(f.image().mean(), img.mean(), rtol=1e-3)

    f = V.ImageFeature(img.copy())
    V.Saturation(0.0, 0.0).transform(f)  # factor 0 => grayscale
    assert np.allclose(f.image()[..., 0], f.image()[..., 1], atol=1e-4)

    f = V.ImageFeature(img.copy())
    V.Hue(0.0, 0.0).transform(f)  # zero rotation => identity
    np.testing.assert_allclose(f.image(), img, atol=1e-3)

    f = V.ImageFeature(img.copy())
    V.ChannelOrder(seed=3).transform(f)
    np.testing.assert_allclose(
        sorted(f.image().sum(axis=(0, 1)).tolist()),
        sorted(img.sum(axis=(0, 1)).tolist()), rtol=1e-5)

    f = V.ImageFeature(img.copy())
    f[V.ImageFeature.boxes] = np.asarray([[4.0, 4.0, 12.0, 12.0]])
    V.Crop((0.25, 0.25, 0.75, 0.75)).transform(f)
    assert f.image().shape == (8, 8, 3)
    np.testing.assert_allclose(f[V.ImageFeature.boxes], [[0, 0, 8, 8]])

    f = V.ImageFeature(img.copy())
    V.RandomCrop(8, 8, seed=1).transform(f)
    assert f.image().shape == (8, 8, 3)

    f = V.ImageFeature(img.copy())
    V.RandomResize([8, 32], seed=2).transform(f)
    assert f.image().shape[0] in (8, 32)

    f = V.ImageFeature(img.copy())
    V.Filler(0.0, 0.0, 0.5, 0.5, value=7.0).transform(f)
    assert np.all(f.image()[:8, :8] == 7.0)

    f = V.ImageFeature(img.copy())
    V.PixelNormalizer(img).transform(f)
    np.testing.assert_allclose(f.image(), 0.0, atol=1e-5)

    f = V.ImageFeature(img.copy())
    V.ChannelScaledNormalizer(10, 20, 30, 0.5).transform(f)
    np.testing.assert_allclose(
        f.image(), (img - [10, 20, 30]) * 0.5, rtol=1e-5)

    f = V.ImageFeature(img.copy())
    V.ColorJitter(seed=5).transform(f)
    assert f.image().shape == img.shape
