"""Distributed engine tests on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing "distributed" in-process
(Spark local-mode ≙ xla_force_host_platform_device_count, SURVEY.md §4):
AllReduceParameterSpec / FP16ParameterSpec / DistriOptimizerSpec analogs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import SGD, Adam, Trigger, Top1Accuracy, Optimizer
from bigdl_tpu.parallel import (
    Engine, AllReduceParameter, DistriOptimizer,
    flatten_params, unflatten_params, pad_to_multiple, compress, decompress,
)


@pytest.fixture
def mesh():
    return Engine.create_mesh([("data", 8)])


class TestFlatParams:
    def test_round_trip(self):
        tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
                "b": {"c": jnp.ones(5, jnp.float32)}}
        flat, spec = flatten_params(tree)
        assert flat.shape == (11,)
        back = unflatten_params(flat, spec)
        np.testing.assert_allclose(np.asarray(back["a"]), np.arange(6).reshape(2, 3))
        np.testing.assert_allclose(np.asarray(back["b"]["c"]), np.ones(5))

    def test_pad_to_multiple(self):
        flat = jnp.ones(10)
        padded, size = pad_to_multiple(flat, 8)
        assert size == 16 and padded.shape == (16,)
        np.testing.assert_allclose(np.asarray(padded[10:]), 0.0)

    def test_bf16_compress_is_truncation(self):
        """≙ FP16CompressedTensor: upper 16 bits of the f32 pattern
        (parameters/FP16CompressedTensor.scala:270-278) == bfloat16."""
        x = jnp.asarray([1.2345678, -3.1415926, 1e-8], jnp.float32)
        c = decompress(compress(x))
        np.testing.assert_allclose(np.asarray(c), np.asarray(x), rtol=1e-2)


class TestAllReduceParameter:
    def test_reduce_scatter_then_gather_matches_mean(self, mesh):
        arp = AllReduceParameter("data", compress_dtype=None)
        n = 8

        def body(g):
            owned = arp.aggregate(g)
            return arp.all_gather_weights(owned)

        grads = jnp.arange(n * 16, dtype=jnp.float32).reshape(n, 16)
        out = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False))(grads.reshape(-1))
        # every device's gathered copy equals mean over devices
        expect = np.mean(np.asarray(grads), axis=0)
        got = np.asarray(out).reshape(n, 16)
        for d in range(n):
            np.testing.assert_allclose(got[d], expect, rtol=1e-5)


def _xor_samples(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 2).astype(np.float32)
    labels = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(np.float32) + 1.0
    return [Sample(x[i], np.array([labels[i]])) for i in range(n)]


def _mlp():
    model = nn.Sequential()
    model.add(nn.Linear(2, 32))
    model.add(nn.Tanh())
    model.add(nn.Linear(32, 2))
    model.add(nn.LogSoftMax())
    return model


class TestDistriOptimizer:
    @pytest.mark.parametrize("sync", ["sharded", "allreduce"])
    def test_trains_xor_on_8_devices(self, mesh, sync):
        samples = _xor_samples(256)
        model = _mlp()
        opt = DistriOptimizer(
            model=model, dataset=DataSet.array(samples),
            criterion=nn.ClassNLLCriterion(), batch_size=64,
            end_when=Trigger.max_epoch(60),
            mesh=mesh, parameter_sync=sync)
        opt.set_optim_method(Adam(learning_rate=0.05))
        trained = opt.optimize()
        results = trained.evaluate_on(_xor_samples(64, seed=1), [Top1Accuracy()],
                                      batch_size=64)
        acc, _ = results[0][1].result()
        assert acc > 0.85, f"{sync}: accuracy {acc}"

    def test_sharded_matches_local_single_step(self, mesh):
        """Semantic oracle à la RefDistriOptimizer (optim/RefDistriOptimizer.scala):
        one distributed step == one local step on the same global batch."""
        samples = _xor_samples(64, seed=3)
        model_a = _mlp()
        model_b = model_a.clone_module()

        opt_a = Optimizer(model=model_a, dataset=samples,
                          criterion=nn.ClassNLLCriterion(), batch_size=64,
                          end_when=Trigger.max_iteration(1))
        opt_a.set_optim_method(SGD(learning_rate=0.1))
        opt_a.optimize()

        opt_b = DistriOptimizer(model=model_b, dataset=DataSet.array(samples),
                                criterion=nn.ClassNLLCriterion(), batch_size=64,
                                end_when=Trigger.max_iteration(1),
                                mesh=mesh, parameter_sync="sharded",
                                compress_dtype=None)
        opt_b.set_optim_method(SGD(learning_rate=0.1))
        opt_b.optimize()

        wa, _ = model_a.get_parameters()
        wb, _ = model_b.get_parameters()
        np.testing.assert_allclose(np.asarray(wa), np.asarray(wb), atol=2e-5)

    def test_batch_divisibility_enforced(self, mesh):
        samples = _xor_samples(30)
        opt = DistriOptimizer(model=_mlp(), dataset=DataSet.array(samples),
                              criterion=nn.ClassNLLCriterion(), batch_size=30,
                              end_when=Trigger.max_iteration(1), mesh=mesh)
        with pytest.raises(ValueError, match="divide"):
            opt.optimize()
