"""Headline benchmark: ResNet-50 synthetic-ImageNet training throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "imgs/sec/chip", "vs_baseline": N}

The reference publishes no imgs/sec table (BASELINE.md) — its north-star
target is ResNet-50 data-parallel at >70% of reference-JAX MFU. The
denominator is MEASURED in-process: bigdl_tpu/models/jax_resnet_ref.py is a
framework-free raw-JAX ResNet-50 step timed side-by-side on the same chip;
vs_baseline = ours_imgs_per_sec / (0.70 * ref_imgs_per_sec)  (>1.0 beats
the north star). If the ref measurement fails, falls back to the round-2
assumed constant (50%-MFU reference -> 0.35 target MFU) and says so in
``detail.baseline_source``.

detail also carries the LeNet-MNIST epoch wall-clock named by BASELINE.json.

Run: python bench.py [--batch N] [--iters N] [--model resnet50]
"""

import argparse
import json
import sys
import time

RESNET50_FWD_FLOPS_PER_IMG = 4.09e9  # 224x224, standard bottleneck count
TRAIN_FLOPS_MULT = 3.0               # fwd + bwd ≈ 3x fwd
TARGET_MFU = 0.35                    # 70% of an assumed 50%-MFU reference JAX impl

PEAK_FLOPS = {                       # bf16 peak per chip
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 459e12,
    "TPU v4": 275e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
    "cpu": 5e11,
}


def peak_flops(device) -> float:
    # the observability cost model owns the peak table (and honors the
    # BIGDL_PEAK_FLOPS env override); the local dict stays as the
    # documented fallback for a broken import
    try:
        from bigdl_tpu.observability.costmodel import device_peaks

        return device_peaks(device)["flops_per_s"]
    except Exception:
        pass
    kind = getattr(device, "device_kind", "cpu")
    for k, v in PEAK_FLOPS.items():
        if k.lower() in str(kind).lower():
            return v
    return PEAK_FLOPS["cpu"]


def _row_stamps(dev, mesh_shape=None):
    """Provenance fields every bench row carries: perf_gate refuses to
    compare rows across device kinds, and a jax upgrade explains a step
    change in the trend line."""
    import jax

    return {
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
        "jax_version": jax.__version__,
        "mesh_shape": mesh_shape,
    }


def _cost_fields(leg):
    """mfu / membw_util / flops_source for one engine leg's detail row,
    from the cost-model block the engine replay attaches."""
    c = (leg or {}).get("cost") or {}
    overall = c.get("overall") or {}
    sources = {k.get("flops_source")
               for k in (c.get("kinds") or {}).values()
               if k.get("flops_source")}
    return {
        "mfu": overall.get("mfu"),
        "membw_util": overall.get("membw_util"),
        "flops_source": (sources.pop() if len(sources) == 1
                         else ("mixed" if sources else None)),
    }


def main(argv=None):
    """Watchdog orchestrator (imports NO jax itself).

    The axon tunnel's observed failure modes are (a) an UNAVAILABLE error at
    backend init (round-1 BENCH rc=1) and (b) a **hang inside `import jax` /
    first device op** when the tunnel is wedged — a hang no in-process retry
    can survive.  So the real bench runs in a child process under a
    deadline; on timeout or failure the child is killed and a clean CPU
    child (PYTHONPATH="" skips the axon sitecustomize, JAX_PLATFORMS=cpu)
    produces a fallback metric line.  One JSON line is emitted in every
    outcome.
    """
    import os
    import subprocess

    if os.environ.get("BIGDL_BENCH_CHILD"):
        return bench_main(argv)

    argv = list(sys.argv[1:] if argv is None else argv)
    here = os.path.dirname(os.path.abspath(__file__))
    me = os.path.abspath(__file__)
    tpu_timeout = float(os.environ.get("BIGDL_BENCH_TPU_TIMEOUT", "540"))

    # PID-suffixed so concurrent bench invocations never clobber or recover
    # each other's checkpoint; cleaned up in the finally below.
    partial = os.path.join(here, f".bench_partial.{os.getpid()}.json")
    env = dict(os.environ, BIGDL_BENCH_CHILD="1", BIGDL_BENCH_PARTIAL=partial)
    try:
        try:
            proc = subprocess.run([sys.executable, me] + argv, env=env,
                                  cwd=here, stdout=subprocess.PIPE,
                                  timeout=tpu_timeout)
            if proc.returncode == 0 and proc.stdout.strip():
                sys.stdout.buffer.write(proc.stdout)
                _append_history(here, proc.stdout)
                return
            print(f"[bench] primary attempt rc={proc.returncode}; "
                  "falling back", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"[bench] primary attempt exceeded {tpu_timeout}s "
                  "(wedged tunnel?); falling back", file=sys.stderr)
        # The child may have measured the headline and then wedged in a
        # later stage — recover the checkpointed result before resorting
        # to CPU.
        try:
            with open(partial, "rb") as f:
                out = f.read()
            if out.strip():
                json.loads(out)  # refuse a torn/corrupt checkpoint
                print("[bench] recovered measured headline from partial "
                      "checkpoint", file=sys.stderr)
                sys.stdout.buffer.write(out)
                _append_history(here, out)
                return
        except OSError:
            pass
        except ValueError as e:
            print(f"[bench] partial checkpoint unreadable: {e}",
                  file=sys.stderr)
    finally:
        for p in (partial, partial + ".tmp"):
            try:
                os.unlink(p)
            except OSError:
                pass

    # Clean-CPU fallback: PYTHONPATH="" skips the axon sitecustomize so the
    # child cannot wedge.  It runs the *real* smoke config (resnet18, batch 8,
    # 10 iters, NHWC/bf16 — the same shape family as the TPU headline, scaled
    # down) so tunnel-wedged rounds still yield comparable trend numbers.
    env = dict(os.environ, BIGDL_BENCH_CHILD="1", PYTHONPATH="",
               JAX_PLATFORMS="cpu", BIGDL_BENCH_BUDGET="600")
    fallback = []
    skip = False
    for a in argv:  # strip any --model/-m flag (+value); fallback is resnet18
        if skip:
            skip = False
            continue
        if a in ("--model", "-m"):
            skip = True
            continue
        if a.startswith("--model="):
            continue
        fallback.append(a)
    try:
        proc = subprocess.run(
            [sys.executable, me, "--model", "resnet18"] + fallback, env=env,
            cwd=here, stdout=subprocess.PIPE, timeout=660)
        out, rc = proc.stdout, proc.returncode
    except subprocess.TimeoutExpired as e:
        out, rc = b"", 1
        print(f"[bench] CPU fallback exceeded 660s: {e}", file=sys.stderr)
    if not out.strip():  # one JSON line in EVERY outcome
        out = (json.dumps({"metric": "bench_failed", "value": 0.0,
                           "unit": "imgs/sec/chip", "vs_baseline": None,
                           "detail": {"error": f"fallback rc={rc}"}})
               .encode() + b"\n")
    # A CPU row means the tunnel was wedged NOW — but hardware numbers may
    # exist from an earlier window.  Surface the freshest TPU trend row so
    # the fallback line still points at the measured result.
    try:
        rec = json.loads(out.decode().strip().splitlines()[-1])
        last_tpu = None
        hist = (os.environ.get("BIGDL_BENCH_HISTORY")
                or os.path.join(here, "bench_history.jsonl"))
        with open(hist) as f:
            for ln in f:
                try:
                    row = json.loads(ln)
                except ValueError:
                    continue
                if "TPU" in str(row.get("detail", {}).get("device", "")):
                    last_tpu = row
        if last_tpu is not None:
            rec.setdefault("detail", {})["last_measured_tpu"] = {
                k: last_tpu.get(k) for k in ("metric", "value", "vs_baseline",
                                             "ts")}
            rec["detail"]["last_measured_tpu"]["device"] = (
                last_tpu.get("detail", {}).get("device"))
            out = json.dumps(rec).encode() + b"\n"
    except (OSError, ValueError, IndexError) as e:
        print(f"[bench] last-TPU annotation skipped: {e}", file=sys.stderr)
    sys.stdout.buffer.write(out)
    _append_history(here, out)
    sys.exit(rc)


def _append_history(here, stdout_bytes):
    """Append the emitted JSON line (+ UTC timestamp) to bench_history.jsonl
    so trend data survives tunnel-wedged rounds."""
    import datetime
    import os

    try:
        rec = json.loads(stdout_bytes.decode().strip().splitlines()[-1])
        rec["ts"] = datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds")
        path = (os.environ.get("BIGDL_BENCH_HISTORY")
                or os.path.join(here, "bench_history.jsonl"))
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except Exception as e:
        print(f"[bench] history append failed: {e}", file=sys.stderr)


def _lenet_epoch_wallclock(log):
    """LeNet-MNIST epoch wall-clock (BASELINE.json's second metric): one
    synthetic 60k-sample epoch, batch 512, through the standard train step."""
    import jax.numpy as jnp
    from bigdl_tpu.models.perf import run_perf

    batch, n_samples = 512, 60000
    iters = n_samples // batch  # 117
    s = run_perf("lenet5", batch_size=batch, iterations=iters, warmup=2,
                 dtype=jnp.float32, log=log)
    return round(s["time_s"], 3)


def bench_main(argv=None):
    import os

    t_start = time.perf_counter()
    budget = float(os.environ.get("BIGDL_BENCH_BUDGET")
                   or os.environ.get("BIGDL_BENCH_TPU_TIMEOUT", "540"))

    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--model", default="resnet50")
    p.add_argument("--format", default=os.environ.get("BIGDL_BENCH_FORMAT", "NHWC"))
    p.add_argument("--serving", action="store_true",
                   help="Poisson-arrival serving benchmark: continuous-"
                        "batching engine vs GenerationService")
    p.add_argument("--shared-prefix", action="store_true",
                   help="with --serving: prefix-heavy workload (Poisson "
                        "arrivals over N shared prompt templates), "
                        "engine prefix-cache ON vs OFF — emits TTFT "
                        "speedup + hit rate into bench_history.jsonl")
    p.add_argument("--templates", type=int, default=4,
                   help="--shared-prefix: number of shared prompt "
                        "templates")
    p.add_argument("--working-set", type=int, default=0, metavar="N",
                   help="with --serving --shared-prefix: sweep the "
                        "shared-template working set up to N templates "
                        "round-robin against a 2-row device pool, host "
                        "tier sized to the working set vs device-only "
                        "vs cache-disabled — emits the hit-rate-cliff "
                        "A/B (per-point hit rate + TTFT, token parity, "
                        "jit-flat and ledger-conservation flags) into "
                        "bench_history.jsonl")
    p.add_argument("--speculative", action="store_true",
                   help="with --serving: repeated-text workload "
                        "replayed with an int8-draft speculative "
                        "engine vs the plain engine — emits the "
                        "inter-token p50/p99 A/B and the draft "
                        "acceptance rate into bench_history.jsonl")
    p.add_argument("--gamma", type=int, default=8,
                   help="--speculative: draft tokens proposed per "
                        "fused decode round (the int8 draft agrees "
                        "with its float source ~90%% of the time, so "
                        "a wide gamma amortizes dispatch overhead "
                        "hardest)")
    p.add_argument("--quantized", action="store_true",
                   help="with --serving: quantized A/B — the same "
                        "Poisson workload through the engine with "
                        "int8 KV pools + int8 target weights vs the "
                        "fp engine, plus both variants under the "
                        "int8-draft speculative path; emits the "
                        "inter-token p50/p99 speedups, membw_util "
                        "before/after, the logit-divergence quality "
                        "gate and the spec acceptance delta into "
                        "bench_history.jsonl")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="with --serving: multi-replica fleet A/B — one "
                        "shared-prefix Poisson storm through N spawn-"
                        "worker engine replicas routed by prefix "
                        "affinity vs round-robin, plus the mid-storm "
                        "drain drill; emits the affinity TTFT p50 "
                        "speedup, fleet hit-rate gain, zero-loss drain "
                        "verdict and token parity into "
                        "bench_history.jsonl")
    p.add_argument("--tp", type=int, default=0, metavar="N",
                   help="with --serving: tensor-parallel A/B — the "
                        "same Poisson workload through the engine "
                        "SHARDED over an N-way model-axis device mesh "
                        "(host-device mesh on CPU) vs the plain "
                        "single-device engine; emits both paths' TTFT "
                        "and inter-token percentiles + greedy token "
                        "parity into bench_history.jsonl")
    p.add_argument("--qos", action="store_true",
                   help="with --serving: SLO-aware QoS storm — one "
                        "mixed-priority Poisson storm (interactive "
                        "high, standard normal, batch low, plus an "
                        "over-budget greedy tenant) through a 2-slot "
                        "engine with burn-rate shedding, KV-donating "
                        "preemption and per-tenant token buckets, vs "
                        "the SAME high-class traffic uncontended; "
                        "emits the high-class TTFT p50/p99 ratios, "
                        "shed/preempted/rate-limited counts and the "
                        "outcome-conservation verdict into "
                        "bench_history.jsonl (the bar: p50 ratio "
                        "<= 1.25x, every QoS mechanism fired, no "
                        "silent drops)")
    p.add_argument("--trace", action="store_true",
                   help="also dump bench_trace.json — the run's span "
                        "trees + flight-recorder events as Chrome "
                        "trace JSON (open in Perfetto); path override: "
                        "BIGDL_BENCH_TRACE")
    p.add_argument("--profile", type=float, default=None,
                   metavar="SECONDS",
                   help="capture a jax.profiler trace of (up to) the "
                        "first SECONDS of the benchmark run — model/"
                        "engine build, compile, and warmup included "
                        "(observability.profiler); the artifact dir "
                        "lands in detail.profile_artifact")
    p.add_argument("--paged", action="store_true",
                   help="with --serving: paged-KV A/B — one mixed "
                        "short/long storm through the engine in paged "
                        "mode (page-granular block pool) vs dense "
                        "full-window slots at an EQUAL device KV byte "
                        "budget — emits the peak admitted-concurrency "
                        "ratio (bar: >= 3x) + TTFT A/B into "
                        "bench_history.jsonl")
    p.add_argument("--requests", type=int, default=24,
                   help="--serving: workload size")
    p.add_argument("--rate", type=float, default=20.0,
                   help="--serving: Poisson arrival rate (req/s)")
    args = p.parse_args(argv)

    if args.serving and args.tp and args.tp > 1:
        # the host-device mesh for --serving --tp: XLA reads this at
        # backend creation (first device use is below), so setting it
        # here still takes effect — on CPU it yields exactly tp
        # virtual devices, on real accelerators it is inert. Gated on
        # --serving: forcing virtual devices under a training bench
        # would divide its intra-op threads and poison the trend row.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.tp}")

    import jax

    # Persistent compilation cache: ResNet-50 on the axon tunnel can compile
    # slowly enough to eat the whole watchdog budget; a prior successful run
    # (same code, same shapes) turns that into a cache hit.
    from bigdl_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    dev = None
    for attempt in range(1, 4):
        try:
            dev = jax.devices()[0]
            break
        except Exception as e:  # transient UNAVAILABLE from the tunnel
            print(f"[bench] backend init attempt {attempt}/3 failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            if attempt == 3:
                raise
            time.sleep(10.0 * attempt)
    if args.serving:
        return _serving_bench(args, dev)

    on_tpu = "tpu" in dev.platform.lower() or dev.platform == "axon"
    batch = args.batch or int(os.environ.get(
        "BIGDL_BENCH_BATCH", "256" if on_tpu else "8"))
    iters = args.iters or (20 if on_tpu else 10)
    model = args.model
    if not on_tpu and model == "resnet50":
        # CPU backend in the primary child (no TPU visible): run the smoke
        # config, not the 540s-eating TPU headline, and keep the metric name
        # distinct so CPU rows never pollute the TPU trend line.
        model = "resnet18"

    import jax.numpy as jnp

    from bigdl_tpu.models.perf import run_perf
    from bigdl_tpu.version import __version__

    log = lambda *a, **k: print(*a, file=sys.stderr, **k)  # noqa: E731
    # Same config family on CPU as on TPU (NHWC + bf16 compute, f32 masters)
    # so tunnel-wedged rounds exercise — and time — the real code path.
    fmt = args.format if model.startswith("resnet") else "NCHW"
    # start as close to the profiled work as bench controls: run_perf
    # builds + compiles + warms + measures, all inside the capture
    prof = _start_profile(args.profile)
    s = run_perf(model, batch_size=batch, iterations=iters,
                 dtype=jnp.bfloat16 if model != "lenet5" else jnp.float32,
                 format=fmt,
                 master_f32=model != "lenet5",
                 log=log)

    def checkpoint(result):
        """Atomically persist the headline so the watchdog parent can
        recover it if a later dispatch hard-wedges inside a C call (where
        SIGALRM cannot preempt) — round-5 lesson: the first TPU window in
        three rounds lost a measured headline to exactly this."""
        path = os.environ.get("BIGDL_BENCH_PARTIAL")
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(result) + "\n")
        os.replace(tmp, path)

    imgs_per_sec = s["records_per_sec"]
    # per-image train FLOPs: XLA's own count from the lowered step when
    # run_perf extracted one, else the standard bottleneck constant
    if s.get("cost_source") == "xla":
        flops_per_img = s["flops_per_iter"] / batch
        flops_source = "xla"
    elif model == "resnet50":
        flops_per_img = RESNET50_FWD_FLOPS_PER_IMG * TRAIN_FLOPS_MULT
        flops_source = "analytic"
    else:
        flops_per_img, flops_source = None, None
    if model == "resnet50":
        mfu = imgs_per_sec * flops_per_img / peak_flops(dev)
        # Until the measured denominator lands: assumed 50%-MFU reference.
        ref_mfu, baseline_source = None, "assumed_0.50_mfu_ref"
        vs_baseline = mfu / TARGET_MFU
        metric = "resnet50_synthetic_imagenet_train_throughput"
    else:
        # No MFU north-star applies to fallback models — vs_baseline is an
        # honest null (advisor finding, round 1), but a measured FLOP
        # count still yields a real MFU figure worth trending.
        mfu = (imgs_per_sec * flops_per_img / peak_flops(dev)
               if flops_per_img else 0.0)
        ref_mfu, baseline_source = None, None
        vs_baseline = None
        metric = f"{model}_synthetic_train_throughput"

    result = {
        "metric": metric,
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(vs_baseline, 4) if vs_baseline is not None else None,
        "detail": {
            "version": __version__,
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "batch": batch, "iters": iters,
            "dtype": "f32" if model == "lenet5" else "bf16",
            "format": fmt, "ms_per_iter": s["ms_per_iter"],
            "mfu": round(mfu, 4),
            "flops_source": flops_source,
            **_row_stamps(dev),
            "ref_jax_mfu": None,
            "baseline_source": baseline_source,
            "target_mfu": TARGET_MFU,
            "lenet_mnist_epoch_s": None,
        },
    }
    checkpoint(result)  # headline measured — survives a wedge in ANY later stage

    # Measured denominator: raw-JAX ResNet-50 on the same chip; leave
    # >=180s of watchdog budget for its compile+run.
    if (model == "resnet50" and not os.environ.get("BIGDL_BENCH_NOREF")
            and time.perf_counter() - t_start < budget - 180):
        try:
            from bigdl_tpu.models.jax_resnet_ref import run_ref_perf
            r = run_ref_perf(batch_size=batch, iterations=max(5, iters // 2),
                             log=log)
            ref_achieved = (r["records_per_sec"] * RESNET50_FWD_FLOPS_PER_IMG
                            * TRAIN_FLOPS_MULT)
            result["detail"]["ref_jax_mfu"] = round(
                ref_achieved / peak_flops(dev), 4)
            result["vs_baseline"] = round(
                imgs_per_sec / (0.70 * r["records_per_sec"]), 4)
            result["detail"]["baseline_source"] = "measured_raw_jax_ref"
            checkpoint(result)
        except Exception as e:
            print(f"[bench] ref-jax denominator failed: {e}", file=sys.stderr)

    if os.environ.get("BIGDL_BENCH_TEST_WEDGE"):
        # fault injection (tests): simulate a hard tunnel wedge after the
        # headline is measured — the watchdog must recover the partial
        time.sleep(1e6)

    remaining = budget - (time.perf_counter() - t_start)
    if not os.environ.get("BIGDL_BENCH_NOLENET") and remaining > 90:
        # Self-deadline for slow-but-returning dispatches; a hard wedge is
        # covered by the partial-file checkpoint above.
        import signal

        def _deadline(signum, frame):
            raise TimeoutError("lenet epoch stage deadline")

        old = signal.signal(signal.SIGALRM, _deadline)
        signal.alarm(max(30, int(remaining - 60)))
        try:
            result["detail"]["lenet_mnist_epoch_s"] = _lenet_epoch_wallclock(log)
        except Exception as e:
            print(f"[bench] lenet epoch metric failed: {e}", file=sys.stderr)
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)

    art = _finish_profile(prof)
    if art is not None:
        result["detail"]["profile_artifact"] = art
    result["detail"]["memory"] = _memory_snapshot()
    _record_bench_metrics(result, model)
    _dump_prometheus_snapshot()
    if args.trace:
        _dump_chrome_trace()
    print(json.dumps(result))


def _serving_bench(args, dev):
    """`--serving`: replay ONE Poisson-arrival workload through the
    continuous-batching engine and through GenerationService; emit one
    JSON line (p50/p99 latency, TTFT, aggregate tokens/sec for both
    paths) into bench_history.jsonl + the Prometheus snapshot so the
    serving perf trajectory is tracked alongside the training headline.
    vs_baseline is the p99-latency speedup over GenerationService
    (> 1.0: the engine's tail is shorter). Engine rows also carry the
    usage ledger's goodput block (padding waste, utilization, tokens
    per device-second) and the per-tenant token/device-second
    breakdown; `scripts/perf_gate.py` additionally gates goodput
    between comparable rows, skipping rows that predate the field.

    `--serving --shared-prefix`: the prefix-heavy variant — Poisson
    arrivals over N shared prompt templates, replayed through the
    engine with its prefix cache ON vs OFF. vs_baseline is the p50
    TTFT speedup of the cached path (>1.0: the cache pays for itself;
    the acceptance bar is >=2x), and detail carries the hit rate,
    reused-token fraction, and greedy token-parity flag.
    `scripts/perf_gate.py` gates CI on the p99 TTFT of consecutive
    comparable rows.

    `--serving --shared-prefix --working-set N`: the tiered-cache
    sweep — round-robin template workloads at working sets from inside
    to N-templates past a 2-row device pool, each replayed through a
    host-tier engine (host rows = working set), a device-only engine,
    and a cache-disabled oracle. value is the headline tiered hit rate
    at the deepest point, vs_baseline the tiered/device-only hit-rate
    gain there (the device-only leg LRU-thrashes once the working set
    exceeds its rows; the bar is >=2x at >=4x the budget), and detail
    carries the per-point sweep plus token-parity / jit-flat /
    ledger-conservation flags. perf_gate gates the headline hit rate
    (higher-is-better) and the tiered leg's p50/p99 TTFT.

    `--serving --speculative`: the speculative A/B — one repeated-text
    Poisson workload replayed through the engine with an int8-clone
    draft (gamma proposals per fused round) vs the plain engine.
    vs_baseline is the inter-token p50 speedup of the speculative path
    (>1.0: the draft pays for itself), and detail carries both paths'
    inter-token p50/p99, the acceptance rate, and the greedy
    token-parity flag; perf_gate gates the speculative row's p99
    inter-token (and TTFT / goodput) between comparable runs.

    `--serving --fleet N`: the multi-replica fleet A/B — one shared-
    prefix Poisson storm replayed through N spawn-worker engine
    replicas (each its own process, model, engine, budget-bound prefix
    trie) routed by the PrefixAffinityRouter vs round-robin, plus the
    mid-storm drain drill (one replica drains and rejoins; zero lost
    requests is the bar). value/vs_baseline is the affinity-vs-round-
    robin client TTFT p50 speedup (>1.0: content-aware routing lands
    first tokens sooner), and detail carries both legs' percentiles,
    the fleet hit rates, the routing tallies, the drain block, the
    token-parity verdict against a single-replica reference, plus the
    affinity leg's capacity stamp (detail.capacity: fleet headroom,
    replicas-needed, per-role device-wall split) and SLO error-budget
    floor (detail.slo_budget.remaining_min). perf_gate gates the
    speedup, the fleet hit rate, the affinity leg's p99 TTFT, the
    capacity headroom band, and the calm-run budget floor between
    comparable rows.

    `--serving --tp N`: the tensor-parallel A/B — the same Poisson
    workload through the engine SHARDED over an N-way model-axis
    device mesh (a host-device mesh on CPU: the flag forces N virtual
    host devices) vs the plain single-device engine. vs_baseline is
    the inter-token p50 ratio unsharded/sharded (on CPU expect < 1.0
    — collectives cost and host compute doesn't shrink; the row
    tracks that overhead and pins greedy token parity + the sharded
    mesh/pool attribution block). perf_gate gates the sharded row's
    p99 TTFT / inter-token / goodput between comparable runs.

    `--serving --qos`: the QoS storm — one mixed-priority Poisson
    storm (interactive high-class, standard normal, batch low, plus a
    deliberately over-budget "greedy" tenant) through a 2-slot engine
    running the full QoS stack (burn-rate shedding of low/normal,
    KV-donating preemption, per-tenant token buckets), vs the SAME
    high-class traffic replayed uncontended. value is the storm leg's
    high-class TTFT p99; vs_baseline is the storm/uncontended
    high-class TTFT p50 ratio (~1.0: shedding + preemption hold the
    top class at its uncontended self; the bar is <= 1.25x). detail
    carries both legs' percentiles, per-class TTFT, the shed /
    preempted / rate-limited counts and the outcome-conservation
    verdict (every submission ended in exactly one terminal state).
    perf_gate gates the p50 ratio at the 1.25 ceiling, requires every
    QoS mechanism to have fired, conservation to hold, and bands the
    storm leg's high-class TTFT between comparable rows; the p99
    ratio rides along ungated (max-of-few-samples tail)."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.serving.benchmark import (
        run_paged_comparison, run_poisson_comparison, run_qos_storm,
        run_quantized_comparison, run_shared_prefix_comparison,
        run_speculative_comparison, run_tp_comparison,
        run_working_set_sweep,
    )
    from bigdl_tpu.utils import random as rnd
    from bigdl_tpu.version import __version__

    log = lambda *a, **k: print(*a, file=sys.stderr, **k)  # noqa: E731
    if args.fleet and args.fleet > 1:
        # the fleet bench spawns its own worker processes (each builds
        # the recipe model on the shared seed) — no parent-side model
        from bigdl_tpu.serving.fleet import run_fleet_comparison

        prof = _start_profile(args.profile)
        res = run_fleet_comparison(
            n_replicas=args.fleet, n_requests=args.requests,
            rate_hz=args.rate, log=log)
        result = {
            "metric": "serving_fleet_ttft_p50_speedup",
            "value": res["ttft_p50_speedup"],
            "unit": "ratio",
            # vs_baseline > 1.0: the affinity leg's median first token
            # lands sooner than round-robin's on the same storm
            "vs_baseline": res["ttft_p50_speedup"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **res,
                # headline hop decomposition: the affinity leg's mean
                # seconds per fleet hop (route/rpc_submit/queue/
                # prefill/first_token/decode/stream)
                "hops": (res.get("affinity") or {}).get("hops"),
            },
        }
        _record_fleet_metrics(res)
        art = _finish_profile(prof)
        if art is not None:
            result["detail"]["profile_artifact"] = art
        result["detail"]["memory"] = _memory_snapshot()
        _dump_prometheus_snapshot()
        if args.trace:
            _dump_chrome_trace()
        print(json.dumps(result))
        return
    rnd.set_seed(7)
    model = TransformerLM(128, embed_dim=64, num_heads=4, num_kv_heads=2,
                          num_layers=2, max_len=128, use_rope=True)
    model.evaluate()
    prof = _start_profile(args.profile)
    if args.tp and args.tp > 1:
        res = run_tp_comparison(
            model, tp=args.tp, n_requests=args.requests,
            rate_hz=args.rate, max_slots=4, prefill_chunk=8,
            prefill_rows=2, log=log)
        result = {
            "metric": "serving_tp_tokens_per_sec",
            "value": res["sharded"]["tokens_per_sec"],
            "unit": "tokens/sec",
            # vs_baseline > 1.0: the sharded path's steady-state
            # decode gap is shorter than single-device (expect < 1.0
            # on a CPU host mesh, where collectives cost and compute
            # doesn't shrink — the row exists to track the overhead)
            "vs_baseline": res["inter_token_p50_ratio"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev, mesh_shape={"model": args.tp}),
                **_cost_fields(res["sharded"]),
                **res,
            },
        }
        _record_tp_metrics(res)
    elif args.qos:
        res = run_qos_storm(
            model, n_requests=args.requests, rate_hz=args.rate,
            max_slots=2, prefill_chunk=8, prefill_rows=2, log=log)
        result = {
            "metric": "serving_qos_high_ttft_p99",
            "value": res["qos"]["ttft"]["p99"],
            "unit": "seconds",
            # vs_baseline ~ 1.0: under a mixed-priority storm the
            # high class's MEDIAN first token lands where it would
            # uncontended — shedding + preemption absorbed the
            # contention (the acceptance bar is <= 1.25x)
            "vs_baseline": res["high_ttft_p50_ratio"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **_cost_fields(res["qos"]),
                **res,
            },
        }
        _record_qos_metrics(res)
    elif args.quantized:
        res = run_quantized_comparison(
            model, n_requests=args.requests, rate_hz=args.rate,
            max_slots=4, prefill_chunk=8, prefill_rows=2,
            gamma=args.gamma, log=log)
        result = {
            "metric": "serving_quantized_tokens_per_sec",
            "value": res["quantized"]["tokens_per_sec"],
            "unit": "tokens/sec",
            # vs_baseline > 1.0: the int8 engine's steady-state decode
            # gap is shorter than fp's on the same workload (on CPU
            # expect ~1.0 — int8 matmuls aren't faster on host BLAS;
            # the row pins the quality gate and byte attribution, and
            # membw-bound accelerators collect the speedup)
            "vs_baseline": res["inter_token_p50_speedup"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **_cost_fields(res["quantized"]),
                **res,
            },
        }
        _record_quantized_metrics(res)
    elif args.speculative:
        res = run_speculative_comparison(
            model, n_requests=args.requests, rate_hz=args.rate,
            max_slots=4, prefill_chunk=8, prefill_rows=2,
            gamma=args.gamma, log=log)
        result = {
            "metric": "serving_speculative_tokens_per_sec",
            "value": res["spec"]["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": res["inter_token_p50_speedup"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **_cost_fields(res["spec"]),
                **res,
            },
        }
        _record_speculative_metrics(res)
    elif args.paged:
        res = run_paged_comparison(
            model, n_requests=max(args.requests, 32),
            dense_slots=2, paged_slots=8, page_size=4,
            prefill_chunk=8, prefill_rows=2, log=log)
        result = {
            "metric": "serving_paged_admitted_concurrency",
            "value": res["paged"]["peak_admitted_concurrency"],
            "unit": "requests",
            # vs_baseline > 1.0: paged mode admitted more concurrent
            # requests than dense full-window slots from the SAME
            # device KV bytes (the acceptance bar is >= 3x on the
            # short-heavy storm)
            "vs_baseline": res["admitted_concurrency_ratio"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **_cost_fields(res["paged"]),
                **res,
            },
        }
        _record_paged_metrics(res)
    elif args.shared_prefix and args.working_set:
        res = run_working_set_sweep(
            model, working_sets=(2, max(4, args.working_set)),
            device_rows=2, rate_hz=args.rate, max_slots=4,
            prefill_chunk=8, prefill_rows=2, template_len=16, log=log)
        result = {
            "metric": "serving_tiered_prefix_hit_rate",
            "value": res["headline"]["tiered_hit_rate"],
            "unit": "fraction",
            # vs_baseline > 1.0: the host tier holds the hit rate the
            # device-only cache loses past its budget (the acceptance
            # bar is >=2x at a working set >=4x the device pool)
            "vs_baseline": res["headline"]["hit_rate_gain"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **_cost_fields(res["tiered"]),
                **res,
            },
        }
        _record_working_set_metrics(res)
    elif args.shared_prefix:
        res = run_shared_prefix_comparison(
            model, n_requests=args.requests, rate_hz=args.rate,
            max_slots=4, prefill_chunk=8, prefill_rows=2,
            n_templates=args.templates, template_len=96, log=log)
        result = {
            "metric": "serving_shared_prefix_tokens_per_sec",
            "value": res["cached"]["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": res["ttft_p50_speedup"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **_cost_fields(res["cached"]),
                **res,
            },
        }
        _record_shared_prefix_metrics(res)
    else:
        res = run_poisson_comparison(model, n_requests=args.requests,
                                     rate_hz=args.rate, max_slots=4,
                                     prefill_chunk=8, log=log)
        result = {
            "metric": "serving_poisson_tokens_per_sec",
            "value": res["engine"]["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": res["p99_speedup"],
            "detail": {
                "version": __version__,
                "device": str(getattr(dev, "device_kind", dev.platform)),
                **_row_stamps(dev),
                **_cost_fields(res["engine"]),
                **res,
            },
        }
        _record_serving_metrics(res)
    art = _finish_profile(prof)
    if art is not None:
        result["detail"]["profile_artifact"] = art
    result["detail"]["memory"] = _memory_snapshot()
    _dump_prometheus_snapshot()
    if args.trace:
        _dump_chrome_trace()
    print(json.dumps(result))


def _start_profile(seconds):
    """``--profile``: begin a jax.profiler capture of the measured run
    plus a timer that stops it at the requested bound (whichever of
    run-end / timer comes first wins — stop_capture is idempotent).
    Returns an opaque handle for ``_finish_profile``, or None."""
    if not seconds or seconds <= 0:
        return None
    import threading

    from bigdl_tpu.observability import profiler

    try:
        path = profiler.start_capture()
    except Exception as e:
        print(f"[bench] profiler capture unavailable: {e}",
              file=sys.stderr)
        return None
    timer = threading.Timer(min(float(seconds), profiler.MAX_SECONDS),
                            profiler.stop_capture, kwargs={"strict": False})
    timer.daemon = True
    timer.start()
    print(f"[bench] profiling up to {seconds}s -> {path}",
          file=sys.stderr)
    return {"path": path, "timer": timer}


def _finish_profile(prof):
    """Stop the ``--profile`` capture (if the timer has not already)
    and return the artifact directory, or None when not profiling."""
    if prof is None:
        return None
    from bigdl_tpu.observability import profiler

    prof["timer"].cancel()
    try:
        profiler.stop_capture(strict=False)
    except Exception as e:
        print(f"[bench] profiler stop failed: {e}", file=sys.stderr)
    return prof["path"]


def _memory_snapshot():
    """One device-memory sample for the result's detail block: total
    bytes in use, per-device source, and the per-pool attribution the
    run registered (KV pools, params, optimizer slots). Never lets
    telemetry break the bench."""
    try:
        from bigdl_tpu.observability.memory import default_monitor

        s = default_monitor().sample()
        return {
            "bytes_in_use": s["bytes_in_use"],
            "devices": [{k: d[k] for k in
                         ("device", "bytes_in_use", "limit_bytes",
                          "source")}
                        for d in s["devices"]],
            "pools": s["pools"],
        }
    except Exception as e:
        print(f"[bench] memory snapshot failed: {e}", file=sys.stderr)
        return None


def _record_shared_prefix_metrics(res):
    """Mirror the shared-prefix comparison into the observability
    registry (``path`` label: cached / uncached) so live scrapes and
    bench snapshots share one schema. Never lets telemetry break the
    bench."""
    try:
        from bigdl_tpu import observability as obs

        # instruments resolve against the CURRENT default registry —
        # the same one the snapshot dump renders
        ins = obs.serving_bench_instruments()
        for path in ("cached", "uncached"):
            _record_path_metrics(ins, res[path], path)
        if res.get("ttft_p50_speedup") is not None:
            ins.prefix_ttft_p50_speedup().set(res["ttft_p50_speedup"])
        pc = res["cached"].get("prefix_cache", {})
        if pc.get("enabled"):
            ins.prefix_hit_rate().set(pc["hit_rate"])
            ins.prefix_reused_fraction().set(pc["reused_fraction"])
    except Exception as e:
        print(f"[bench] shared-prefix metrics registry update failed: "
              f"{e}", file=sys.stderr)


def _record_working_set_metrics(res):
    """Mirror the working-set sweep's HEADLINE point into the
    observability registry (``path`` label: tiered / device_only) so
    live scrapes and bench snapshots share one schema. Never lets
    telemetry break the bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path in ("tiered", "device_only"):
            _record_path_metrics(ins, res[path], path)
        head = res.get("headline") or {}
        if head.get("tiered_hit_rate") is not None:
            ins.tiered_hit_rate().set(head["tiered_hit_rate"])
        if head.get("hit_rate_gain") is not None:
            ins.tiered_hit_rate_gain().set(head["hit_rate_gain"])
    except Exception as e:
        print(f"[bench] working-set metrics registry update failed: "
              f"{e}", file=sys.stderr)


def _record_speculative_metrics(res):
    """Mirror the speculative A/B into the observability registry
    (``path`` label: spec_on / spec_off) so live scrapes and bench
    snapshots share one schema. Never lets telemetry break the
    bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path, key in (("spec_on", "spec"), ("spec_off", "nospec")):
            _record_path_metrics(ins, res[key], path)
        if res.get("acceptance_rate") is not None:
            ins.spec_acceptance_rate().set(res["acceptance_rate"])
        if res.get("inter_token_p50_speedup") is not None:
            ins.spec_inter_token_p50_speedup().set(
                res["inter_token_p50_speedup"])
    except Exception as e:
        print(f"[bench] speculative metrics registry update failed: "
              f"{e}", file=sys.stderr)


def _record_quantized_metrics(res):
    """Mirror the quantized A/B into the observability registry
    (``path`` label: quant_on / quant_off / quant_spec_fp /
    quant_spec_int8) plus the unlabeled quality-gate scalars. Never
    lets telemetry break the bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path, key in (("quant_on", "quantized"),
                          ("quant_off", "fp_baseline"),
                          ("quant_kv_only", "kv_only"),
                          ("quant_spec_fp", "spec_fp"),
                          ("quant_spec_int8", "spec_int8")):
            _record_path_metrics(ins, res[key], path)
        if res.get("inter_token_p50_speedup") is not None:
            ins.quant_inter_token_p50_speedup().set(
                res["inter_token_p50_speedup"])
        if res.get("inter_token_p99_speedup") is not None:
            ins.quant_inter_token_p99_speedup().set(
                res["inter_token_p99_speedup"])
        q = res.get("quality") or {}
        if q.get("logit_div_rel") is not None:
            ins.quant_logit_div_rel().set(q["logit_div_rel"])
        if q.get("acceptance_delta") is not None:
            ins.quant_acceptance_delta().set(q["acceptance_delta"])
        ratio = (res.get("capacity") or {}).get("row_bytes_ratio")
        if ratio is not None:
            ins.quant_row_bytes_ratio().set(ratio)
    except Exception as e:
        print(f"[bench] quantized metrics registry update failed: {e}",
              file=sys.stderr)


def _record_fleet_metrics(res):
    """Mirror the fleet A/B into the observability registry (``path``
    label: fleet_affinity / fleet_round_robin) so live scrapes and
    bench snapshots share one schema. Never lets telemetry break the
    bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path, key in (("fleet_affinity", "affinity"),
                          ("fleet_round_robin", "round_robin")):
            _record_path_metrics(ins, res[key], path)
        if res.get("ttft_p50_speedup") is not None:
            ins.fleet_ttft_p50_speedup().set(res["ttft_p50_speedup"])
        hit = (res.get("affinity", {}).get("fleet") or {}).get("hit_rate")
        if hit is not None:
            ins.fleet_hit_rate().set(hit)
    except Exception as e:
        print(f"[bench] fleet metrics registry update failed: {e}",
              file=sys.stderr)


def _record_qos_metrics(res):
    """Mirror the QoS storm A/B into the observability registry
    (``path`` label: qos_storm / qos_uncontended) plus the unlabeled
    ratio / mechanism-count scalars. Never lets telemetry break the
    bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path, key in (("qos_storm", "qos"),
                          ("qos_uncontended", "uncontended")):
            _record_path_metrics(ins, res[key], path)
        if res.get("high_ttft_p50_ratio") is not None:
            ins.qos_high_ttft_p50_ratio().set(
                res["high_ttft_p50_ratio"])
        if res.get("high_ttft_p99_ratio") is not None:
            ins.qos_high_ttft_p99_ratio().set(
                res["high_ttft_p99_ratio"])
        for key, gauge in (("preempted", ins.qos_preempted),
                           ("shed", ins.qos_shed),
                           ("rate_limited", ins.qos_rate_limited)):
            if res.get(key) is not None:
                gauge().set(res[key])
    except Exception as e:
        print(f"[bench] qos metrics registry update failed: {e}",
              file=sys.stderr)


def _record_goodput_metrics(ins, block, path):
    """Mirror one serving result's usage-ledger goodput block (emitted
    by the engine replays in ``bigdl_tpu.serving.benchmark``) into the
    ``path``-labelled bench gauges."""
    g = block.get("goodput") or {}
    if g.get("tokens_per_device_second") is not None:
        ins.goodput_tokens_per_device_second.labels(path).set(
            g["tokens_per_device_second"])
    if g.get("padding_waste_mean") is not None:
        ins.padding_waste_mean.labels(path).set(g["padding_waste_mean"])


def _record_path_metrics(ins, r, path):
    """Mirror ONE serving-comparison leg's standard result block
    (throughput, latency / TTFT / inter-token percentiles, goodput)
    into the ``path``-labelled bench gauges — the shared body of every
    per-variant recorder, so a gauge added here reaches all of them."""
    ins.tokens_per_sec.labels(path).set(r["tokens_per_sec"])
    if r.get("latency", {}).get("p50") is not None:
        ins.latency_p50.labels(path).set(r["latency"]["p50"])
        ins.latency_p99.labels(path).set(r["latency"]["p99"])
    if r.get("ttft", {}).get("p50") is not None:
        ins.ttft_p50.labels(path).set(r["ttft"]["p50"])
        ins.ttft_p99_by_path.labels(path).set(r["ttft"]["p99"])
    if r.get("inter_token", {}).get("p99") is not None:
        ins.inter_token_p99.labels(path).set(r["inter_token"]["p99"])
    _record_goodput_metrics(ins, r, path)


def _record_paged_metrics(res):
    """Mirror the paged-KV A/B into the observability registry under
    ``path`` labels (``paged`` / ``dense``) plus the unlabeled
    concurrency-ratio / TTFT-speedup / fragmentation scalars. Never
    lets telemetry break the bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path, key in (("paged", "paged"), ("dense", "dense")):
            _record_path_metrics(ins, res[key], path)
        if res.get("admitted_concurrency_ratio") is not None:
            ins.paged_admitted_concurrency_ratio().set(
                res["admitted_concurrency_ratio"])
        if res.get("ttft_p99_speedup") is not None:
            ins.paged_ttft_p99_speedup().set(res["ttft_p99_speedup"])
        frag = (res["paged"].get("paging") or {}).get("fragmentation")
        if frag is not None:
            ins.paged_fragmentation().set(frag)
    except Exception as e:
        print(f"[bench] paged metrics registry update failed: {e}",
              file=sys.stderr)


def _record_tp_metrics(res):
    """Mirror the tensor-parallel A/B into the observability registry
    under ``path`` labels (``tp_sharded`` / ``tp_unsharded``). Never
    lets telemetry break the bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path, key in (("tp_sharded", "sharded"),
                          ("tp_unsharded", "unsharded")):
            _record_path_metrics(ins, res[key], path)
    except Exception as e:
        print(f"[bench] tp metrics registry update failed: {e}",
              file=sys.stderr)


def _record_serving_metrics(res):
    """Mirror the serving comparison into the observability registry
    under a ``path`` label, so live scrapes and bench snapshots share
    one schema. Never lets telemetry break the bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.serving_bench_instruments()
        for path, key in (("engine", "engine"),
                          ("generation_service", "generation_service")):
            r = res[key]
            ins.tokens_per_sec.labels(path).set(r["tokens_per_sec"])
            if r["latency"]["p50"] is not None:
                ins.latency_p50.labels(path).set(r["latency"]["p50"])
                ins.latency_p99.labels(path).set(r["latency"]["p99"])
        eng = res["engine"]
        if eng.get("ttft", {}).get("p99") is not None:
            ins.ttft_p99().set(eng["ttft"]["p99"])
        if eng.get("inter_token", {}).get("p99") is not None:
            ins.inter_token_p99.labels("engine").set(
                eng["inter_token"]["p99"])
        if res.get("p99_speedup") is not None:
            ins.p99_speedup().set(res["p99_speedup"])
        _record_goodput_metrics(ins, eng, "engine")
    except Exception as e:
        print(f"[bench] serving metrics registry update failed: {e}",
              file=sys.stderr)


def _record_bench_metrics(result, model):
    """Mirror the headline numbers into the observability registry —
    bench snapshots and live scrapes then share one metric schema
    (bigdl_* names, all minted in observability/instruments.py — the
    metrics lint holds that line), so the perf trajectory is diffable
    against production telemetry. Never lets telemetry break the
    bench."""
    try:
        from bigdl_tpu import observability as obs

        ins = obs.bench_instruments()
        d = result["detail"]
        ins.imgs_per_sec.labels(model).set(result["value"])
        ins.ms_per_iter.labels(model).set(d["ms_per_iter"])
        ins.mfu.labels(model).set(d["mfu"])
        if result.get("vs_baseline") is not None:
            ins.vs_baseline.labels(model).set(result["vs_baseline"])
        if d.get("lenet_mnist_epoch_s") is not None:
            ins.lenet_epoch_seconds().set(d["lenet_mnist_epoch_s"])
    except Exception as e:
        print(f"[bench] metrics registry update failed: {e}",
              file=sys.stderr)


def _dump_artifact(env_var, filename, writer_name, label):
    """Drop one observability artifact next to the BENCH_*.json trend
    files (path overridable via ``env_var``); ``writer_name`` is the
    ``bigdl_tpu.observability`` export that does the actual write.
    Never lets telemetry break the bench."""
    import os

    try:
        from bigdl_tpu import observability as obs

        path = (os.environ.get(env_var)
                or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                filename))
        getattr(obs, writer_name)(path)
        print(f"[bench] {label} -> {path}", file=sys.stderr)
    except Exception as e:
        print(f"[bench] {label} failed: {e}", file=sys.stderr)


def _dump_chrome_trace():
    """`--trace`: Chrome trace-event JSON of the run (span trees +
    flight-recorder request timelines) alongside bench_metrics.prom —
    one serving benchmark run becomes one Perfetto-loadable artifact."""
    _dump_artifact("BIGDL_BENCH_TRACE", "bench_trace.json",
                   "write_chrome_trace", "chrome trace")


def _dump_prometheus_snapshot():
    """Prometheus text snapshot alongside the BENCH_*.json trend files.
    Includes everything the run put in the default registry — bench
    gauges plus any bigdl_train_* series the perf loops populated."""
    _dump_artifact("BIGDL_BENCH_PROM", "bench_metrics.prom",
                   "write_prometheus", "prometheus snapshot")


if __name__ == "__main__":
    main()
