"""Headline benchmark: ResNet-50 synthetic-ImageNet training throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "imgs/sec/chip", "vs_baseline": N}

The reference publishes no imgs/sec table (BASELINE.md) — its north-star
target is ResNet-50 data-parallel at >70% of reference-JAX MFU. We
therefore report measured imgs/sec/chip and normalize ``vs_baseline``
against that target expressed in MFU: assuming the reference JAX ResNet-50
implementation reaches ~50% MFU, the target is 0.35 absolute MFU;
vs_baseline = measured_MFU / 0.35 (>1.0 beats the north star).

Run: python bench.py [--batch N] [--iters N] [--model resnet50]
"""

import argparse
import json
import sys
import time

RESNET50_FWD_FLOPS_PER_IMG = 4.09e9  # 224x224, standard bottleneck count
TRAIN_FLOPS_MULT = 3.0               # fwd + bwd ≈ 3x fwd
TARGET_MFU = 0.35                    # 70% of an assumed 50%-MFU reference JAX impl

PEAK_FLOPS = {                       # bf16 peak per chip
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 459e12,
    "TPU v4": 275e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
    "cpu": 5e11,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in PEAK_FLOPS.items():
        if k.lower() in str(kind).lower():
            return v
    return PEAK_FLOPS["cpu"]


def _cpu_subprocess_fallback(args):
    """Re-exec this bench on the CPU platform in a clean subprocess.

    Necessary because a committed (or error-cached) backend can't be swapped
    in-process, and the env must skip the axon sitecustomize (PYTHONPATH="")
    so the wedged tunnel isn't dialed again."""
    import os
    import subprocess

    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.abspath(__file__), "--model", "lenet5"]
    if args.batch:
        cmd += ["--batch", str(args.batch)]
    if args.iters:
        cmd += ["--iters", str(args.iters)]
    proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                          cwd=os.path.dirname(os.path.abspath(__file__)))
    sys.stdout.buffer.write(proc.stdout)
    sys.exit(proc.returncode)


def init_backend(args, retries=3, backoff_s=10.0):
    """Backend discovery that survives a flaky axon/TPU tunnel (round-1
    failure mode: one transient UNAVAILABLE at jax.devices() cost the whole
    round's evidence).  Retry with backoff, then degrade to the virtual CPU
    platform via a clean subprocess (exits this process)."""
    import jax

    for attempt in range(1, retries + 1):
        try:
            return jax.devices()[0]
        except Exception as e:  # jax.errors.JaxRuntimeError etc.
            print(f"[bench] backend init attempt {attempt}/{retries} failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            if attempt < retries:
                time.sleep(backoff_s * attempt)
    print("[bench] falling back to CPU platform (subprocess)", file=sys.stderr)
    _cpu_subprocess_fallback(args)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--model", default="resnet50")
    args = p.parse_args(argv)

    dev = init_backend(args)
    on_tpu = "tpu" in dev.platform.lower()
    batch = args.batch or (64 if on_tpu else 4)
    iters = args.iters or (20 if on_tpu else 2)
    model = args.model if on_tpu else "lenet5"
    if args.model != "resnet50":
        model = args.model

    import jax.numpy as jnp

    from bigdl_tpu.models.perf import run_perf

    try:
        s = run_perf(model, batch_size=batch, iterations=iters,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                     log=lambda *a, **k: print(*a, file=sys.stderr, **k))
    except Exception as e:
        if not on_tpu:
            raise
        # TPU run died mid-bench (tunnel wedge): salvage the round with a
        # CPU fallback number rather than emitting nothing.  The TPU backend
        # is already committed in this process (jax_platforms is only
        # consulted at first backend init), so the CPU run MUST happen in a
        # clean subprocess — with PYTHONPATH cleared so the axon
        # sitecustomize doesn't dial the wedged tunnel again.
        print(f"[bench] TPU run failed ({type(e).__name__}: {e}); "
              "retrying on CPU in a subprocess", file=sys.stderr)
        _cpu_subprocess_fallback(args)

    imgs_per_sec = s["records_per_sec"]
    if model == "resnet50":
        achieved = imgs_per_sec * RESNET50_FWD_FLOPS_PER_IMG * TRAIN_FLOPS_MULT
        mfu = achieved / peak_flops(dev)
        vs_baseline = mfu / TARGET_MFU
        metric = "resnet50_synthetic_imagenet_train_throughput"
    else:
        # No MFU north-star applies to fallback models — report an honest
        # null rather than an unmeasured 1.0 (advisor finding, round 1).
        mfu = 0.0
        vs_baseline = None
        metric = f"{model}_synthetic_train_throughput"

    print(json.dumps({
        "metric": metric,
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(vs_baseline, 4) if vs_baseline is not None else None,
        "detail": {
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "batch": batch, "iters": iters, "dtype": "bf16" if on_tpu else "f32",
            "ms_per_iter": s["ms_per_iter"], "mfu": round(mfu, 4),
            "target_mfu": TARGET_MFU,
        },
    }))


if __name__ == "__main__":
    main()
